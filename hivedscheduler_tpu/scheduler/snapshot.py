"""State snapshots: the serialized durable projection for O(delta) recovery.

The reference keeps no database (PAPER.md): recovery replays every bound
pod's annotation, which is minutes of scheduling blackout at a 100k-pod
fleet. This module serializes the scheduler's DURABLE PROJECTION — exactly
the state the chaos harness proves restart-equivalent (confirmed-bound
pods with their decoded placements, the preemption checkpoints, applied
health records, the doomed-ledger epoch, and the informer resourceVersion
watermark) — into a chunked, checksummed payload a scheduler-owned
ConfigMap family carries, so recovery becomes snapshot-import plus a
delta replay of only what changed since the watermark
(doc/fault-model.md "HA and snapshot recovery plane").

Format: ``encode`` returns a chunk list whose FIRST element is a small
JSON meta header (schema version, SHA-256 checksum and byte length of the
body, chunk count, compiled-config fingerprint, watermark) and whose
remaining elements are the JSON body split at ``CHUNK_BYTES`` boundaries
(a ConfigMap tops out at 1 MiB; chunks leave headroom for the object
envelope). ``decode`` is the validation ladder — every rung falls back to
full annotation replay rather than guessing:

  1. meta header decodes and carries the expected schema version;
  2. chunk count and reassembled byte length match the header;
  3. SHA-256 of the reassembled body matches;
  4. the config fingerprint matches the running config (a reconfiguration
     between snapshot and recovery invalidates every cell address);
  5. the watermark is not older than ``min_watermark`` (the informer's
     delta floor — a snapshot from before the watch window is stale);
  6. the body decodes and is schema-shaped.

Everything here is pure data transformation — no locks, no I/O — so the
framework can serialize under its lock and write outside it (the PR-3
doomed-ledger flush pattern).
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Optional, Tuple

from ..api.config import Config
from . import wire

# Bump when the body schema changes shape; decode refuses other versions
# (rung 1 of the fallback ladder). The golden schema test pins the
# serialized form in both directions. v2: the body gained the "core"
# section (verbatim cell-level projection) and import switched from
# per-pod re-admission to direct state restore.
SCHEMA_VERSION = 2

# Body bytes per chunk. A ConfigMap caps at 1 MiB total; 900 KB leaves
# headroom for the object envelope and the apiserver's own accounting.
CHUNK_BYTES = 900_000


def config_fingerprint(config: Config) -> str:
    """Identity of the COMPILED scheduling config: the physical topology and
    the VC quota carve-up — everything that gives cell addresses meaning.
    A snapshot taken under a different fingerprint is unusable (its
    addresses may name different hardware), so decode() refuses it and
    recovery replays annotations (which tolerate reconfiguration
    per-placement). Webserver knobs deliberately excluded: retuning a
    deadline must not invalidate snapshots."""
    # STREAMED hashing (doc/hot-path.md "Boot and transport plane"): the
    # digest is fed the exact byte sequence
    # ``json.dumps(canonical, sort_keys=True, separators=(",", ":"))``
    # of the historical canonical dict WITHOUT materializing that dict or
    # its text — at 50k hosts the full form is hundreds of MB of
    # transient strings on every boot. Byte-compatibility invariants the
    # golden test pins: top-level keys are already alphabetical
    # (cellTypes < physicalCells < virtualClusters); per-entry sections
    # are emitted in sorted-key order and each small entry is dumped with
    # the same sort_keys/separators, so the concatenation is identical to
    # the one-shot dumps. Changing ANY byte here invalidates every live
    # snapshot — treat this function as a serialization format.
    pc = config.physical_cluster
    h = hashlib.sha256()

    def dumps(obj) -> bytes:
        return json.dumps(
            obj, sort_keys=True, separators=(",", ":")
        ).encode()

    h.update(b'{"cellTypes":{')
    first = True
    for name in sorted(str(n) for n in pc.cell_types):
        ct = pc.cell_types[name]
        if not first:
            h.update(b",")
        first = False
        h.update(dumps(name) + b":" + dumps({
            "childCellType": str(ct.child_cell_type),
            "childCellNumber": int(ct.child_cell_number),
            "isNodeLevel": bool(ct.is_node_level),
        }))
    h.update(b'},"physicalCells":[')
    for i, spec in enumerate(pc.physical_cells):
        if i:
            h.update(b",")
        h.update(dumps(spec.to_dict()))
    h.update(b'],"virtualClusters":{')
    first = True
    for vcn in sorted(str(v) for v in config.virtual_clusters):
        spec = config.virtual_clusters[vcn]
        if not first:
            h.update(b",")
        first = False
        h.update(dumps(vcn) + b":" + dumps({
            "virtualCells": [
                {"cellType": str(v.cell_type), "cellNumber": int(v.cell_number)}
                for v in spec.virtual_cells
            ],
            "pinnedCells": [
                {"pinnedCellId": str(p.pinned_cell_id)}
                for p in spec.pinned_cells
            ],
        }))
    h.update(b"}}")
    return h.hexdigest()


def encode(
    body: Dict,
    fingerprint: str,
    watermark,
    schema_version: int = SCHEMA_VERSION,
    chunk_bytes: int = CHUNK_BYTES,
    pods_json: Optional[List[str]] = None,
) -> List[str]:
    """Serialize a snapshot body into the chunk list the KubeClient
    persists: ``[meta-json, body-part-0, body-part-1, ...]``.

    ``pods_json`` is the flusher's fast path: pre-serialized JSON texts
    for the entries of ``body["pods"]``, memoized per bound pod across
    flushes (a bound pod's record never changes, so re-dumping the pods
    section — the bulk of the body at fleet scale — every flush was pure
    GC churn). The section-wise assembly below is byte-identical to the
    plain ``json.dumps(body)`` because dicts preserve insertion order
    and the same separators are used throughout."""
    if pods_json is None:
        body_text = json.dumps(body, separators=(",", ":"))
    else:
        parts = []
        for k, v in body.items():
            if k == "pods":
                parts.append('"pods":[' + ",".join(pods_json) + "]")
            else:
                parts.append(
                    json.dumps(k)
                    + ":"
                    + json.dumps(v, separators=(",", ":"))
                )
        body_text = "{" + ",".join(parts) + "}"
    data = body_text.encode()
    chunks = [
        body_text[i: i + chunk_bytes]
        for i in range(0, len(body_text), chunk_bytes)
    ] or [""]
    meta = {
        "schemaVersion": schema_version,
        "checksum": hashlib.sha256(data).hexdigest(),
        "bytes": len(data),
        "chunks": len(chunks),
        "configFingerprint": fingerprint,
        "watermark": watermark,
    }
    return [json.dumps(meta, separators=(",", ":"))] + chunks


def encode_body_wire(
    body: Dict,
    fingerprint: str,
    watermark,
    schema_version: int = SCHEMA_VERSION,
) -> bytes:
    """Pack a snapshot body into one binary KIND_SNAPSHOT frame for the
    hops that never touch the apiserver (HA pre-apply, what-if fork,
    flight-recorder anchor). The durable ConfigMap format stays the JSON
    chunk envelope of ``encode`` — this frame is an IN-PROCESS transport:
    no chunking, no SHA-256 (the wire header's magic/version/length
    framing plus the fingerprint rung below carry the same refusals), and
    the body rides as one C-speed JSON blob inside the frame."""
    return wire.dumps(
        (int(schema_version), str(fingerprint), watermark, wire.Json(body)),
        kind=wire.KIND_SNAPSHOT,
    )


def decode_body_wire(
    buf: bytes,
    expected_fingerprint: str,
    min_watermark=None,
) -> Tuple[Optional[Dict], str]:
    """Validation ladder for ``encode_body_wire`` frames — same contract
    as ``decode``: ``(body, "")`` or ``(None, reason)``, never raises.
    Rungs mirror the JSON envelope's: frame decodes at this build's wire
    version, schema version matches, fingerprint matches, watermark not
    older than the delta floor, body snapshot-shaped."""
    try:
        payload = wire.loads(buf, kind=wire.KIND_SNAPSHOT)
    except wire.WireError as e:
        return None, f"wire frame undecodable: {e}"
    if not (isinstance(payload, tuple) and len(payload) == 4):
        return None, "wire frame is not snapshot-shaped"
    schema_version, fingerprint, watermark, body = payload
    if schema_version != SCHEMA_VERSION:
        return None, (
            f"schema version mismatch: snapshot {schema_version}, "
            f"running {SCHEMA_VERSION}"
        )
    if fingerprint != expected_fingerprint:
        return None, (
            "config fingerprint mismatch (reconfigured since the snapshot)"
        )
    if min_watermark is not None and _watermark_older(
        watermark, min_watermark
    ):
        return None, (
            f"stale watermark: snapshot at {watermark!r}, delta "
            f"floor {min_watermark!r}"
        )
    if not isinstance(body, dict) or not isinstance(body.get("pods"), list):
        return None, "body is not snapshot-shaped (missing pods list)"
    if not isinstance(body.get("core"), dict):
        return None, "body is not snapshot-shaped (missing core projection)"
    return body, ""


def _watermark_older(watermark, floor) -> bool:
    """True when ``watermark`` is provably older than ``floor``. K8s
    resourceVersions are opaque strings that are integers in practice (the
    harness uses plain ints); when either side does not parse as an int the
    comparison is impossible and the snapshot is treated as stale — the
    fallback is always safe, a wrong "fresh" verdict is not."""
    try:
        return int(watermark) < int(floor)
    except (TypeError, ValueError):
        return True


def decode(
    chunks: Optional[List[str]],
    expected_fingerprint: str,
    min_watermark=None,
) -> Tuple[Optional[Dict], str]:
    """Validate + reassemble a persisted chunk list. Returns
    ``(body, "")`` on success or ``(None, reason)`` naming the first rung
    of the fallback ladder that failed — the caller counts it
    (snapshotFallbackCount) and runs the full annotation replay."""
    if not chunks:
        return None, "empty chunk list"
    try:
        meta = json.loads(chunks[0])
    except (TypeError, ValueError) as e:
        return None, f"meta header undecodable: {e}"
    if not isinstance(meta, dict):
        return None, "meta header is not an object"
    if meta.get("schemaVersion") != SCHEMA_VERSION:
        return None, (
            f"schema version mismatch: snapshot {meta.get('schemaVersion')}, "
            f"running {SCHEMA_VERSION}"
        )
    if meta.get("chunks") != len(chunks) - 1:
        return None, (
            f"chunk count mismatch: header says {meta.get('chunks')}, "
            f"got {len(chunks) - 1}"
        )
    body_text = "".join(chunks[1:])
    data = body_text.encode()
    if meta.get("bytes") != len(data):
        return None, (
            f"length mismatch: header says {meta.get('bytes')} bytes, "
            f"got {len(data)} (truncated or padded)"
        )
    checksum = hashlib.sha256(data).hexdigest()
    if meta.get("checksum") != checksum:
        return None, "checksum mismatch (corrupt snapshot)"
    if meta.get("configFingerprint") != expected_fingerprint:
        return None, (
            "config fingerprint mismatch (reconfigured since the snapshot)"
        )
    if min_watermark is not None and _watermark_older(
        meta.get("watermark"), min_watermark
    ):
        return None, (
            f"stale watermark: snapshot at {meta.get('watermark')!r}, delta "
            f"floor {min_watermark!r}"
        )
    try:
        body = json.loads(body_text)
    except ValueError as e:
        return None, f"body undecodable: {e}"
    if not isinstance(body, dict) or not isinstance(body.get("pods"), list):
        return None, "body is not snapshot-shaped (missing pods list)"
    if not isinstance(body.get("core"), dict):
        return None, "body is not snapshot-shaped (missing core projection)"
    body["_meta"] = meta
    return body, ""
