"""State snapshots: the serialized durable projection for O(delta) recovery.

The reference keeps no database (PAPER.md): recovery replays every bound
pod's annotation, which is minutes of scheduling blackout at a 100k-pod
fleet. This module serializes the scheduler's DURABLE PROJECTION — exactly
the state the chaos harness proves restart-equivalent (confirmed-bound
pods with their decoded placements, the preemption checkpoints, applied
health records, the doomed-ledger epoch, and the informer resourceVersion
watermark) — into a chunked, checksummed payload a pluggable
``SnapshotStore`` carries (ConfigMap chunk family by default,
filesystem/S3-shaped object store for projections that outgrow it), so
recovery becomes snapshot-import plus a delta replay of only what changed
since the watermark (doc/fault-model.md "Durable-state plane v2").

Format (schema v3, SECTIONED): ``encode_sections`` returns a chunk list
whose FIRST element is a small JSON manifest (schema version, config
fingerprint, watermark, chunk count, whole-body byte length + SHA-256,
and a ``sections`` table: name, covered chains, byte length, SHA-256 per
section) and whose remaining elements are the CONCATENATED section texts
split at ``CHUNK_BYTES`` boundaries. Sections are one per chain family
(riding the per-chain ``export_projection`` memo) plus ``meta``
(doomed-ledger epoch, chainless groups, orphan pods) and ``health`` (the
applied hardware-health records), so the validation ladder is
SECTION-GRANULAR — a corrupt section invalidates only its chains:

  1. manifest decodes, carries a readable schema version (v3, or v2
     read-only for the rolling upgrade), and a well-formed section table;
  2. the config fingerprint matches the running config (a reconfiguration
     between snapshot and recovery invalidates every cell address);
  3. the watermark is not older than ``min_watermark`` (the informer's
     delta floor — a snapshot from before the watch window is stale);
  4. per SECTION: the manifest's byte range slices out of the reassembled
     body, its SHA-256 matches, and the payload decodes — a failed
     section marks only its chains for annotation replay
     (``_corrupt``), while every healthy section restores wholesale;
  5. the ``meta`` and ``health`` sections are load-bearing for every
     chain, so their corruption (or every family section failing) still
     fails the WHOLE snapshot — the caller falls back to full replay.

Everything here is pure data transformation — no locks, no I/O — so the
framework can serialize under its lock and write outside it (the PR-3
doomed-ledger flush pattern).
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Optional, Tuple

from ..api.config import Config
from . import wire

# Bump when the body schema changes shape; decode refuses versions it
# cannot read (rung 1 of the fallback ladder). The golden schema test pins
# the serialized form in both directions. v2: the body gained the "core"
# section (verbatim cell-level projection) and import switched from
# per-pod re-admission to direct state restore. v3: the body split into
# independently checksummed SECTIONS (one per chain family + meta +
# health) listed in the manifest, making corruption section-granular.
SCHEMA_VERSION = 3

# One schema back stays readable (read-only: restored, then re-persisted
# at SCHEMA_VERSION by the next flush) so a v2->v3 rolling upgrade does
# not cost every replica a full annotation replay.
COMPAT_READ_VERSIONS = (2, SCHEMA_VERSION)

# Body bytes per chunk. A ConfigMap caps at 1 MiB total; 900 KB leaves
# headroom for the object envelope and the apiserver's own accounting.
# (The object-store backend has no such cap but keeps the same chunking —
# one format, two stores.)
CHUNK_BYTES = 900_000

# Reserved section names (everything else is a chain-family section,
# conventionally "family:<i>"). SECTION_BODY is the degenerate monolithic
# layout ``encode`` emits for hand-built bodies: one all-or-nothing
# section covering every chain, exactly v2's blast radius.
SECTION_META = "meta"
SECTION_HEALTH = "health"
SECTION_BODY = "body"


def config_fingerprint(config: Config) -> str:
    """Identity of the COMPILED scheduling config: the physical topology and
    the VC quota carve-up — everything that gives cell addresses meaning.
    A snapshot taken under a different fingerprint is unusable (its
    addresses may name different hardware), so decode() refuses it and
    recovery replays annotations (which tolerate reconfiguration
    per-placement). Webserver knobs deliberately excluded: retuning a
    deadline must not invalidate snapshots."""
    # STREAMED hashing (doc/hot-path.md "Boot and transport plane"): the
    # digest is fed the exact byte sequence
    # ``json.dumps(canonical, sort_keys=True, separators=(",", ":"))``
    # of the historical canonical dict WITHOUT materializing that dict or
    # its text — at 50k hosts the full form is hundreds of MB of
    # transient strings on every boot. Byte-compatibility invariants the
    # golden test pins: top-level keys are already alphabetical
    # (cellTypes < physicalCells < virtualClusters); per-entry sections
    # are emitted in sorted-key order and each small entry is dumped with
    # the same sort_keys/separators, so the concatenation is identical to
    # the one-shot dumps. Changing ANY byte here invalidates every live
    # snapshot — treat this function as a serialization format.
    pc = config.physical_cluster
    h = hashlib.sha256()

    def dumps(obj) -> bytes:
        return json.dumps(
            obj, sort_keys=True, separators=(",", ":")
        ).encode()

    h.update(b'{"cellTypes":{')
    first = True
    for name in sorted(str(n) for n in pc.cell_types):
        ct = pc.cell_types[name]
        if not first:
            h.update(b",")
        first = False
        h.update(dumps(name) + b":" + dumps({
            "childCellType": str(ct.child_cell_type),
            "childCellNumber": int(ct.child_cell_number),
            "isNodeLevel": bool(ct.is_node_level),
        }))
    h.update(b'},"physicalCells":[')
    for i, spec in enumerate(pc.physical_cells):
        if i:
            h.update(b",")
        h.update(dumps(spec.to_dict()))
    h.update(b'],"virtualClusters":{')
    first = True
    for vcn in sorted(str(v) for v in config.virtual_clusters):
        spec = config.virtual_clusters[vcn]
        if not first:
            h.update(b",")
        first = False
        h.update(dumps(vcn) + b":" + dumps({
            "virtualCells": [
                {"cellType": str(v.cell_type), "cellNumber": int(v.cell_number)}
                for v in spec.virtual_cells
            ],
            "pinnedCells": [
                {"pinnedCellId": str(p.pinned_cell_id)}
                for p in spec.pinned_cells
            ],
        }))
    h.update(b"}}")
    return h.hexdigest()


def merge_core_slices(slices: List[Dict]) -> Dict:
    """Merge per-family (or per-chain) core projection slices back into
    the single core body ``restore_projection`` consumes — the same
    merge ``HivedCore.export_projection`` performs over its per-chain
    memo sections, so a sectioned snapshot's healthy families reassemble
    byte-equivalently to the monolithic export."""
    phys: Dict[str, List] = {}
    virt: Dict[str, List] = {}
    free_lists: Dict[str, Dict] = {}
    bad_free: Dict[str, Dict] = {}
    vc_doomed: Dict[str, Dict] = {}
    ot_cells: Dict[str, List[str]] = {}
    vc_free: Dict[str, Dict] = {}
    all_vc_free: Dict[str, Dict] = {}
    total_left: Dict[str, Dict] = {}
    all_vc_doomed: Dict[str, Dict] = {}
    groups: Dict[str, Dict] = {}
    for sec in slices:
        phys.update(sec.get("phys") or {})
        virt.update(sec.get("virt") or {})
        free_lists.update(sec.get("freeLists") or {})
        bad_free.update(sec.get("badFree") or {})
        for vcn, per_chain in (sec.get("vcDoomed") or {}).items():
            vc_doomed.setdefault(vcn, {}).update(per_chain)
        for vcn, addrs in (sec.get("otCells") or {}).items():
            ot_cells.setdefault(vcn, []).extend(addrs)
        counters = sec.get("counters") or {}
        for vcn, per_chain in (counters.get("vcFree") or {}).items():
            vc_free.setdefault(vcn, {}).update(per_chain)
        all_vc_free.update(counters.get("allVCFree") or {})
        total_left.update(counters.get("totalLeft") or {})
        all_vc_doomed.update(counters.get("allVCDoomed") or {})
        groups.update(sec.get("groups") or {})
    return {
        "phys": phys,
        "virt": virt,
        "freeLists": free_lists,
        "badFree": bad_free,
        "vcDoomed": vc_doomed,
        "otCells": ot_cells,
        "counters": {
            "vcFree": vc_free,
            "allVCFree": all_vc_free,
            "totalLeft": total_left,
            "allVCDoomed": all_vc_doomed,
        },
        "groups": groups,
    }


def section_text(payload: Dict, pods_json: Optional[List[str]] = None) -> str:
    """Serialize one section payload, splicing the flusher's memoized
    per-pod JSON texts into the ``pods`` entry when provided — the PR-7
    fast path (a bound pod's record never changes, so re-dumping the pods
    bulk every flush was pure GC churn). Byte-identical to the plain
    ``json.dumps(payload)`` because dicts preserve insertion order and the
    same separators are used throughout."""
    if pods_json is None:
        return json.dumps(payload, separators=(",", ":"))
    parts = []
    for k, v in payload.items():
        if k == "pods":
            parts.append('"pods":[' + ",".join(pods_json) + "]")
        else:
            parts.append(
                json.dumps(k) + ":" + json.dumps(v, separators=(",", ":"))
            )
    return "{" + ",".join(parts) + "}"


def encode_sections(
    sections: List[Tuple[str, Optional[List[str]], str]],
    fingerprint: str,
    watermark,
    chunk_bytes: int = CHUNK_BYTES,
) -> List[str]:
    """Serialize pre-rendered sections into the v3 chunk list the
    SnapshotStore persists: ``[manifest-json, body-part-0, ...]``.

    ``sections`` is an ordered list of ``(name, chains, text)`` — chains
    is the list of chain names the section covers (None for the reserved
    meta/health/body sections). The body is the concatenation of the
    section texts; the manifest records each section's byte range (by
    order) and SHA-256 so decode can validate and fall back per section.
    """
    manifest_sections = []
    for name, chains, text in sections:
        data = text.encode()
        entry = {
            "name": name,
            "bytes": len(data),
            "sha256": hashlib.sha256(data).hexdigest(),
        }
        if chains is not None:
            entry["chains"] = [str(c) for c in chains]
        manifest_sections.append(entry)
    body_text = "".join(text for _, _, text in sections)
    data = body_text.encode()
    chunks = [
        body_text[i: i + chunk_bytes]
        for i in range(0, len(body_text), chunk_bytes)
    ] or [""]
    manifest = {
        "schemaVersion": SCHEMA_VERSION,
        "checksum": hashlib.sha256(data).hexdigest(),
        "bytes": len(data),
        "chunks": len(chunks),
        "configFingerprint": fingerprint,
        "watermark": watermark,
        "sections": manifest_sections,
    }
    return [json.dumps(manifest, separators=(",", ":"))] + chunks


def encode(
    body: Dict,
    fingerprint: str,
    watermark,
    schema_version: int = SCHEMA_VERSION,
    chunk_bytes: int = CHUNK_BYTES,
    pods_json: Optional[List[str]] = None,
) -> List[str]:
    """Serialize a MERGED snapshot body into a persistable chunk list.

    At ``SCHEMA_VERSION`` this emits the degenerate single-``body``-section
    v3 envelope (all-or-nothing, v2's blast radius) — the sectioned fast
    path lives in the framework flusher, which renders per-family sections
    and calls ``encode_sections`` directly. Passing ``schema_version=2``
    emits the legacy v2 envelope verbatim (the rolling-upgrade read-compat
    tests exercise decode against it)."""
    if schema_version == 2:
        body_text = section_text(body, pods_json)
        data = body_text.encode()
        chunks = [
            body_text[i: i + chunk_bytes]
            for i in range(0, len(body_text), chunk_bytes)
        ] or [""]
        meta = {
            "schemaVersion": 2,
            "checksum": hashlib.sha256(data).hexdigest(),
            "bytes": len(data),
            "chunks": len(chunks),
            "configFingerprint": fingerprint,
            "watermark": watermark,
        }
        return [json.dumps(meta, separators=(",", ":"))] + chunks
    text = section_text(body, pods_json)
    return encode_sections(
        [(SECTION_BODY, None, text)], fingerprint, watermark, chunk_bytes
    )


def encode_body_wire(
    body: Dict,
    fingerprint: str,
    watermark,
    schema_version: int = SCHEMA_VERSION,
) -> bytes:
    """Pack a snapshot body into one binary KIND_SNAPSHOT frame for the
    hops that never touch the apiserver (HA pre-apply, what-if fork,
    flight-recorder anchor). The durable format stays the sectioned chunk
    envelope — this frame is an IN-PROCESS transport: no chunking, no
    SHA-256 (the wire header's magic/version/length framing plus the
    fingerprint rung below carry the same refusals), and the MERGED body
    rides as one C-speed JSON blob inside the frame (both ends are always
    the same build, so no sectioning and no one-back compat here)."""
    return wire.dumps(
        (int(schema_version), str(fingerprint), watermark, wire.Json(body)),
        kind=wire.KIND_SNAPSHOT,
    )


def decode_body_wire(
    buf: bytes,
    expected_fingerprint: str,
    min_watermark=None,
) -> Tuple[Optional[Dict], str]:
    """Validation ladder for ``encode_body_wire`` frames — same contract
    as ``decode``: ``(body, "")`` or ``(None, reason)``, never raises.
    Rungs mirror the JSON envelope's: frame decodes at this build's wire
    version, schema version matches, fingerprint matches, watermark not
    older than the delta floor, body snapshot-shaped."""
    try:
        payload = wire.loads(buf, kind=wire.KIND_SNAPSHOT)
    except wire.WireError as e:
        return None, f"wire frame undecodable: {e}"
    if not (isinstance(payload, tuple) and len(payload) == 4):
        return None, "wire frame is not snapshot-shaped"
    schema_version, fingerprint, watermark, body = payload
    if schema_version != SCHEMA_VERSION:
        return None, (
            f"schema version mismatch: snapshot {schema_version}, "
            f"running {SCHEMA_VERSION}"
        )
    if fingerprint != expected_fingerprint:
        return None, (
            "config fingerprint mismatch (reconfigured since the snapshot)"
        )
    if min_watermark is not None and _watermark_older(
        watermark, min_watermark
    ):
        return None, (
            f"stale watermark: snapshot at {watermark!r}, delta "
            f"floor {min_watermark!r}"
        )
    if not isinstance(body, dict) or not isinstance(body.get("pods"), list):
        return None, "body is not snapshot-shaped (missing pods list)"
    if not isinstance(body.get("core"), dict):
        return None, "body is not snapshot-shaped (missing core projection)"
    return body, ""


def _watermark_older(watermark, floor) -> bool:
    """True when ``watermark`` is provably older than ``floor``. K8s
    resourceVersions are opaque strings that are integers in practice (the
    harness uses plain ints); when either side does not parse as an int the
    comparison is impossible and the snapshot is treated as stale — the
    fallback is always safe, a wrong "fresh" verdict is not."""
    try:
        return int(watermark) < int(floor)
    except (TypeError, ValueError):
        return True


def _single_family(body: Dict) -> List[Dict]:
    """The ``_families`` view of a monolithic body (v2 envelope or the
    single-``body``-section v3 layout): one healthy pseudo-family covering
    every chain (``chains=None``), so the import path's per-family doom
    gate degenerates to the historical global gate."""
    return [{
        "name": SECTION_BODY,
        "chains": None,
        "ok": True,
        "core": body.get("core") or {},
        "pods": body.get("pods") or [],
    }]


def _shape_error(body) -> str:
    if not isinstance(body, dict) or not isinstance(body.get("pods"), list):
        return "body is not snapshot-shaped (missing pods list)"
    if not isinstance(body.get("core"), dict):
        return "body is not snapshot-shaped (missing core projection)"
    return ""


def _decode_v2(
    meta: Dict, chunks: List[str]
) -> Tuple[Optional[Dict], str]:
    """The legacy v2 whole-body ladder (read-only compat: rungs 2-6 of the
    historical six-rung ladder, all-or-nothing). A v2 body that passes is
    returned in the merged shape with an all-healthy single family; the
    next flush re-persists it at v3."""
    if meta.get("chunks") != len(chunks) - 1:
        return None, (
            f"chunk count mismatch: header says {meta.get('chunks')}, "
            f"got {len(chunks) - 1}"
        )
    body_text = "".join(chunks[1:])
    data = body_text.encode()
    if meta.get("bytes") != len(data):
        return None, (
            f"length mismatch: header says {meta.get('bytes')} bytes, "
            f"got {len(data)} (truncated or padded)"
        )
    checksum = hashlib.sha256(data).hexdigest()
    if meta.get("checksum") != checksum:
        return None, "checksum mismatch (corrupt snapshot)"
    try:
        body = json.loads(body_text)
    except ValueError as e:
        return None, f"body undecodable: {e}"
    err = _shape_error(body)
    if err:
        return None, err
    body["_meta"] = meta
    body["_families"] = _single_family(body)
    body["_corrupt"] = {"sections": [], "chains": []}
    body["_chainless"] = {"groups": {}, "pods": []}
    return body, ""


def _section_valid(text: str, entry: Dict) -> bool:
    """The per-section integrity rung: exact byte length + sha256. A
    separate function so the chaos sensitivity meta-test can no-op it and
    prove the pinned store-fault seeds then FAIL (the validation is
    load-bearing, not decorative)."""
    data = text.encode()
    return len(data) == entry["bytes"] and (
        hashlib.sha256(data).hexdigest() == entry["sha256"]
    )


def decode(
    chunks: Optional[List[str]],
    expected_fingerprint: str,
    min_watermark=None,
) -> Tuple[Optional[Dict], str]:
    """Validate + reassemble a persisted chunk list. Returns
    ``(snap, "")`` on success or ``(None, reason)`` naming the first rung
    of the fallback ladder that failed — the caller counts it
    (snapshotFallbackCount) and runs the full annotation replay.

    On success ``snap`` is the MERGED body (healthy sections only) plus
    bookkeeping the import path consumes:

    - ``snap["_meta"]``: the validated manifest;
    - ``snap["_families"]``: per chain-family records ``{name, chains,
      ok, core, pods}`` (one pseudo-family with ``chains=None`` for
      monolithic layouts) — the import path's unit of doom-gating and
      demotion;
    - ``snap["_corrupt"]``: ``{"sections": [...], "chains": [...]}`` for
      the family sections that failed their rung — those chains replay
      from annotations (partial fallback) while the rest restore.

    Global refusals (whole snapshot unusable → ``None``): unreadable or
    unknown-schema manifest, config fingerprint mismatch, stale
    watermark, corrupt ``meta``/``health``/``body`` section, or every
    chain-family section corrupt."""
    if not chunks:
        return None, "empty chunk list"
    try:
        meta = json.loads(chunks[0])
    except (TypeError, ValueError) as e:
        return None, f"meta header undecodable: {e}"
    if not isinstance(meta, dict):
        return None, "meta header is not an object"
    if meta.get("schemaVersion") not in COMPAT_READ_VERSIONS:
        return None, (
            f"schema version mismatch: snapshot {meta.get('schemaVersion')}, "
            f"running {SCHEMA_VERSION} (reads {COMPAT_READ_VERSIONS})"
        )
    if meta.get("configFingerprint") != expected_fingerprint:
        return None, (
            "config fingerprint mismatch (reconfigured since the snapshot)"
        )
    if min_watermark is not None and _watermark_older(
        meta.get("watermark"), min_watermark
    ):
        return None, (
            f"stale watermark: snapshot at {meta.get('watermark')!r}, delta "
            f"floor {min_watermark!r}"
        )
    if meta.get("schemaVersion") == 2:
        return _decode_v2(meta, chunks)

    manifest_sections = meta.get("sections")
    if not (
        isinstance(manifest_sections, list)
        and manifest_sections
        and all(
            isinstance(s, dict)
            and isinstance(s.get("name"), str)
            and isinstance(s.get("bytes"), int)
            and s["bytes"] >= 0
            and isinstance(s.get("sha256"), str)
            for s in manifest_sections
        )
    ):
        return None, "manifest section table malformed"

    # NOTE deliberately absent global rungs: chunk count, whole-body byte
    # length, and whole-body checksum are recorded in the manifest (the
    # scrubber and ops tooling read them) but are NOT refusal rungs at v3
    # — a dropped or truncated chunk shifts every later section's byte
    # range so those sections fail their OWN sha rung, while sections
    # before the damage stay restorable. Failing globally here would
    # reintroduce exactly the all-or-nothing cliff this schema removes.
    body_text = "".join(chunks[1:])

    payloads: Dict[str, Dict] = {}
    corrupt_sections: List[str] = []
    corrupt_chains: List[str] = []
    offset = 0
    for entry in manifest_sections:
        name = entry["name"]
        text = body_text[offset: offset + entry["bytes"]]
        offset += entry["bytes"]
        ok = _section_valid(text, entry)
        payload = None
        if ok:
            try:
                payload = json.loads(text)
            except ValueError:
                payload = None
            if not isinstance(payload, dict):
                ok = False
        if ok:
            payloads[name] = payload
        else:
            corrupt_sections.append(name)
            corrupt_chains.extend(str(c) for c in entry.get("chains") or ())

    if SECTION_BODY in (e["name"] for e in manifest_sections):
        # Monolithic layout: one section, v2 semantics.
        if SECTION_BODY in corrupt_sections:
            return None, "body section corrupt"
        body = payloads[SECTION_BODY]
        err = _shape_error(body)
        if err:
            return None, err
        body["_meta"] = meta
        body["_families"] = _single_family(body)
        body["_corrupt"] = {"sections": [], "chains": []}
        body["_chainless"] = {"groups": {}, "pods": []}
        return body, ""

    # Sectioned layout: meta + health are load-bearing for every chain.
    if SECTION_META in corrupt_sections:
        return None, "meta section corrupt"
    if SECTION_HEALTH in corrupt_sections:
        return None, "health section corrupt"
    meta_payload = payloads.get(SECTION_META)
    health_payload = payloads.get(SECTION_HEALTH)
    if meta_payload is None or health_payload is None:
        return None, "manifest missing meta/health sections"

    families: List[Dict] = []
    any_ok = False
    for entry in manifest_sections:
        name = entry["name"]
        if name in (SECTION_META, SECTION_HEALTH):
            continue
        chains = [str(c) for c in entry.get("chains") or ()]
        fam = {"name": name, "chains": chains, "ok": name in payloads}
        if fam["ok"]:
            payload = payloads[name]
            fam["core"] = payload.get("core") or {}
            fam["pods"] = payload.get("pods") or []
            if not isinstance(fam["pods"], list) or not isinstance(
                fam["core"], dict
            ):
                fam["ok"] = False
                fam["core"], fam["pods"] = {}, []
                corrupt_sections.append(name)
                corrupt_chains.extend(chains)
        else:
            fam["core"], fam["pods"] = {}, []
        any_ok = any_ok or fam["ok"]
        families.append(fam)
    if not any_ok:
        return None, "every chain-family section corrupt"

    core = merge_core_slices([f["core"] for f in families if f["ok"]])
    core["groups"].update(meta_payload.get("groups") or {})
    pods: List = []
    for f in families:
        if f["ok"]:
            pods.extend(f["pods"])
    pods.extend(meta_payload.get("pods") or [])
    body = {
        "doomedEpoch": meta_payload.get("doomedEpoch"),
        "health": health_payload,
        "core": core,
        "pods": pods,
        "_meta": meta,
        "_families": families,
        "_corrupt": {
            "sections": corrupt_sections,
            "chains": sorted(set(corrupt_chains)),
        },
        # The chain-less remainder (groups with no chain yet + orphan
        # pods) lives in the meta section; the partial-import path
        # re-merges it after demoting doom-diverged families.
        "_chainless": {
            "groups": meta_payload.get("groups") or {},
            "pods": meta_payload.get("pods") or [],
        },
    }
    return body, ""
