"""The scheduling framework: the bridge between K8s and the core algorithm.

Python equivalent of the reference's ``pkg/scheduler/scheduler.go`` (L53-745):
it owns the pod-schedule-status map (the ground truth of the scheduling view),
serializes scheduling per CELL CHAIN (the reference uses one global lock,
scheduler.go:104-108; see scheduler.locks and doc/hot-path.md "The
lock-sharding contract" — filter/bind/preempt calls for disjoint chains
proceed concurrently, whole-cluster mutators take the total-order global
mode, and HIVED_GLOBAL_LOCK=1 restores the single-lock behavior), executes
the assume-bind trick on the filter path, insists on previous binds,
force-binds when the default scheduler stalls, and replays bound pods at
startup for crash recovery.

Instead of client-go informers, the framework exposes plain event-handler
methods (``add_pod``/``update_pod``/``delete_pod``, ``add_node``/...) that an
informer loop (``scheduler.informer``), a test harness, or a simulator drives
— the same seam the reference's test suite exploits
(hived_algorithm_test.go:41-64).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from .. import common
from ..api import constants, extender as ei, types as api
from ..api.config import Config
from ..algorithm.core import HivedCore, get_allocated_pod_index, group_chain
from ..algorithm.group import GroupState
from ..algorithm.placement import PhaseStats
from . import audit as audit_mod
from . import health as health_mod
from . import recorder as recorder_mod
from . import snapshot as snapshot_mod
from . import tracing
from . import weather as weather_mod
from .decisions import GATE_APISERVER_OUTAGE, DecisionJournal
from .defrag import DefragController
from .locks import ChainShardedLock
from .tracing import LatencyHistogram
from .types import (
    Node,
    Pod,
    PodScheduleResult,
    PodScheduleStatus,
    PodState,
    QuarantineRecord,
    SchedulingPhase,
    extract_pod_bind_info,
    extract_pod_scheduling_spec,
    has_pod_preempt_info,
    is_allocated_state,
    is_bound,
    is_interested,
    is_node_healthy,
    new_binding_pod,
)


class KubeClient:
    """The thin slice of the K8s API the framework writes through: pod binds.

    Production deployments plug in :class:`~hivedscheduler_tpu.scheduler.kube.
    KubeAPIClient`; tests plug in a fake that records binds. Reads go through
    the framework's own node/pod caches (the reference reads via listers,
    writes via kClient; scheduler.go:57-95).
    """

    def bind_pod(self, binding_pod: Pod) -> None:
        """Write the binding (target node + annotations) to the cluster
        (reference: internal/utils.go:291-314 ``BindPod``)."""
        raise NotImplementedError

    # Optional capabilities below: default no-ops so simulations and fakes
    # that only care about binds keep working unchanged. Production
    # (KubeAPIClient) implements all three; RetryingKubeClient wraps them
    # with the same backoff policy as binds.

    def patch_pod_annotations(
        self, pod: Pod, annotations: Dict[str, Optional[str]]
    ) -> None:
        """Merge-patch annotations onto a live pod (None value = remove).
        Used to checkpoint preemption reservations onto preemptor pods
        (doc/fault-model.md "Preemption plane")."""

    def persist_scheduler_state(self, payload: str) -> None:
        """Write the scheduler-owned state blob (the doomed ledger) to its
        ConfigMap."""

    def load_scheduler_state(self) -> Optional[str]:
        """Read the scheduler-owned state blob; None when absent."""
        return None

    def persist_snapshot(self, chunks: List[str]) -> None:
        """Write a state snapshot (scheduler.snapshot chunk list: meta
        header + body chunks) to the scheduler-owned snapshot ConfigMap
        family. Implementations must commit the meta header LAST so a
        crash mid-write never yields a valid-looking torn snapshot."""

    def load_snapshot(self) -> Optional[List[str]]:
        """Read the persisted snapshot chunk list; None when absent."""
        return None

    def read_lease(self) -> Optional[Dict]:
        """Read the leader-election Lease: ``{"spec": {...},
        "resourceVersion": ...}`` or None when absent."""
        return None

    def write_lease(self, spec: Dict, resource_version=None) -> None:
        """Write the leader Lease spec, guarded by the optimistic
        ``resource_version`` precondition when given (two standbys racing
        for an expired lease must not both win)."""

    def evict_pod(self, pod: Pod) -> None:
        """Delete a pod (stranded-gang remediation). The informer's DELETED
        event then releases its cells through the normal lifecycle."""


class NullKubeClient(KubeClient):
    """A no-op client for simulations: binds are recorded, not executed."""

    def __init__(self) -> None:
        self.bound_pods: List[Pod] = []

    def bind_pod(self, binding_pod: Pod) -> None:
        self.bound_pods.append(binding_pod)


class SchedulerMetrics:
    """Latency metrics (SURVEY.md §5 build note: the reference has none; the
    north-star metric is gang-schedule p50 latency), including the per-phase
    filter breakdown: lock-wait and core-schedule are recorded here, the
    leaf-cell search inside placement accumulates into the core's shared
    PhaseStats (merged by HivedScheduler.get_metrics)."""

    # Ring of the most recent samples: bounded memory, and the per-scrape
    # percentile sort stays O(window log window) no matter the uptime.
    WINDOW = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.filter_latencies_s: List[float] = []
        self._next_slot = 0
        self.filter_count = 0
        self.bind_count = 0
        self.preempt_count = 0
        self.wait_count = 0
        # Fault-plane counters (doc/fault-model.md): bind write-path retries
        # and terminal failures (RetryingKubeClient), plus pods quarantined
        # during recovery replay.
        self.bind_retry_count = 0
        self.bind_give_up_count = 0
        self.bind_terminal_count = 0
        self.quarantine_count = 0
        # Preempt/reconfig-plane counters: retry rounds cut short by the
        # per-request deadline budget, doomed-ledger ConfigMap writes (and
        # writes that exhausted their retries), and preemption recoveries
        # (replayed vs cancelled) at restart.
        self.request_deadline_exceeded_count = 0
        self.ledger_persist_count = 0
        self.ledger_persist_failure_count = 0
        self.preemption_recovered_count = 0
        self.preemption_cancelled_on_recovery_count = 0
        # Health-plane counters (doc/fault-model.md "Hardware health
        # plane"): transitions actually applied to the core, observations
        # held by the flap damper, held transitions later settled, doomed
        # dooms whose ledger writes were coalesced into one ConfigMap
        # write, and stranded-gang evictions issued.
        self.health_transition_count = 0
        self.health_damped_count = 0
        self.health_settled_count = 0
        # Node update events skipped by the unchanged-projection fast path
        # (no global-lock acquisition; doc/hot-path.md "Warehouse-scale
        # profile" — a relist at fleet scale re-delivers every node).
        self.node_event_noop_count = 0
        # Pending-pod plane (doc/hot-path.md "Pending-pod plane"): filter
        # calls answered from the negative-filter cache — a repeated WAIT
        # whose rejection certificate's version vector was unchanged, so
        # no lock section or placement descent ran.
        self.fast_wait_count = 0
        self.ledger_coalesced_count = 0
        self.stranded_eviction_count = 0
        # Elastic gang plane (doc/fault-model.md "Elastic gang plane"):
        # gangs shrunk in place instead of evicted, shrinks aborted
        # (survivor annotation patch failed and was rolled back),
        # opportunistic gangs grown into idle capacity, and the
        # defragmenter's proposal/migration/cancel counts.
        self.gang_shrink_count = 0
        self.gang_shrink_abort_count = 0
        self.gang_grow_count = 0
        self.defrag_proposal_count = 0
        self.defrag_migration_count = 0
        self.defrag_cancel_count = 0
        # HA / snapshot recovery plane (doc/fault-model.md "HA and snapshot
        # recovery plane"): snapshot ConfigMap writes (and failures),
        # recoveries that fell back from a present-but-unusable snapshot to
        # the full annotation replay, and bind writes refused because this
        # process no longer holds the leader lease.
        self.snapshot_persist_count = 0
        self.snapshot_persist_failure_count = 0
        self.snapshot_fallback_count = 0
        # Durable-state plane v2 (doc/fault-model.md): chain-family
        # sections demoted to the scoped annotation replay (corrupt or
        # doom-diverged) while the rest of the snapshot restored — the
        # partial fallback that replaced the all-or-nothing cliff.
        self.snapshot_section_fallback_count = 0
        self.deposed_bind_refused_count = 0
        # Control-plane weather plane (doc/fault-model.md): bind writes
        # refused retriably because the apiserver is in blackout (the
        # bind POST itself could not land), and filter verdicts answered
        # as degraded WAITs off the projection during blackout.
        self.outage_bind_refused_count = 0
        self.outage_wait_count = 0
        # Framework-side phases (same accumulator/formatter as the core's
        # leaf-cell-search stats, so the merged "phases" payload is uniform).
        self.phase_stats = PhaseStats()
        # Fixed-bucket latency histograms (Prometheus exposition,
        # doc/observability.md): filter and preempt verbs end-to-end, the
        # bind kube write, and per-pod recovery replay. Each takes its own
        # micro-lock — never the scheduler chain locks.
        self.hist_filter = LatencyHistogram()
        self.hist_preempt = LatencyHistogram()
        self.hist_bind = LatencyHistogram()
        self.hist_recovery_replay = LatencyHistogram()

    def observe_filter(
        self,
        seconds: float,
        outcome: str,
        lock_wait_s: float = 0.0,
        core_schedule_s: Optional[float] = None,
    ) -> None:
        self.hist_filter.observe(seconds)
        with self._lock:
            self.filter_count += 1
            if len(self.filter_latencies_s) < self.WINDOW:
                self.filter_latencies_s.append(seconds)
            else:
                self.filter_latencies_s[self._next_slot] = seconds
                self._next_slot = (self._next_slot + 1) % self.WINDOW
            self.phase_stats.add("lockWait", lock_wait_s)
            if core_schedule_s is not None:
                # None = the insist-on-previous-bind path, which never enters
                # the core; counts stay consistent with actual schedule calls.
                self.phase_stats.add("coreSchedule", core_schedule_s)
            if outcome == "bind":
                self.bind_count += 1
            elif outcome == "preempt":
                self.preempt_count += 1
            else:
                self.wait_count += 1

    def observe_preempt_routine(self, seconds: float) -> None:
        """End-to-end preempt verb latency (probe/commit/cancel alike)."""
        self.hist_preempt.observe(seconds)

    def observe_bind_write(self, seconds: float) -> None:
        """The bind_routine kube write (includes any retry backoff)."""
        self.hist_bind.observe(seconds)

    def observe_recovery_replay(self, seconds: float) -> None:
        """One bound pod's recovery replay (recover() / informer boot)."""
        self.hist_recovery_replay.observe(seconds)

    def observe_bind_retry(self) -> None:
        with self._lock:
            self.bind_retry_count += 1

    def observe_bind_give_up(self) -> None:
        with self._lock:
            self.bind_give_up_count += 1

    def observe_bind_terminal(self) -> None:
        with self._lock:
            self.bind_terminal_count += 1

    def observe_quarantine(self) -> None:
        with self._lock:
            self.quarantine_count += 1

    def observe_deadline_exceeded(self) -> None:
        with self._lock:
            self.request_deadline_exceeded_count += 1

    def observe_ledger_persist(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self.ledger_persist_count += 1
            else:
                self.ledger_persist_failure_count += 1

    def observe_preemption_recovery(self, recovered: bool) -> None:
        with self._lock:
            if recovered:
                self.preemption_recovered_count += 1
            else:
                self.preemption_cancelled_on_recovery_count += 1

    def observe_health_transition(self) -> None:
        with self._lock:
            self.health_transition_count += 1

    def observe_health_damped(self) -> None:
        with self._lock:
            self.health_damped_count += 1

    def observe_health_settled(self) -> None:
        with self._lock:
            self.health_settled_count += 1

    def observe_node_event_noop(self) -> None:
        with self._lock:
            self.node_event_noop_count += 1

    def observe_fast_wait(self) -> None:
        with self._lock:
            self.fast_wait_count += 1

    def observe_ledger_coalesced(self, n: int) -> None:
        with self._lock:
            self.ledger_coalesced_count += n

    def observe_stranded_eviction(self) -> None:
        with self._lock:
            self.stranded_eviction_count += 1

    def observe_gang_shrink(self) -> None:
        with self._lock:
            self.gang_shrink_count += 1

    def observe_gang_shrink_abort(self) -> None:
        with self._lock:
            self.gang_shrink_abort_count += 1

    def observe_gang_grow(self) -> None:
        with self._lock:
            self.gang_grow_count += 1

    def observe_defrag_proposal(self) -> None:
        with self._lock:
            self.defrag_proposal_count += 1

    def observe_defrag_migration(self) -> None:
        with self._lock:
            self.defrag_migration_count += 1

    def observe_defrag_cancel(self) -> None:
        with self._lock:
            self.defrag_cancel_count += 1

    def observe_snapshot_persist(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self.snapshot_persist_count += 1
            else:
                self.snapshot_persist_failure_count += 1

    def observe_snapshot_fallback(self) -> None:
        with self._lock:
            self.snapshot_fallback_count += 1

    def observe_snapshot_section_fallback(self, sections: int = 1) -> None:
        with self._lock:
            self.snapshot_section_fallback_count += sections

    def observe_deposed_bind_refused(self) -> None:
        with self._lock:
            self.deposed_bind_refused_count += 1

    def observe_outage_bind_refused(self) -> None:
        with self._lock:
            self.outage_bind_refused_count += 1

    def observe_outage_wait(self) -> None:
        with self._lock:
            self.outage_wait_count += 1

    def snapshot(self) -> Dict:
        with self._lock:
            lat = sorted(self.filter_latencies_s)
            n = len(lat)

            def pct(p: float) -> float:
                if n == 0:
                    return 0.0
                # Nearest-rank: the ceil(p*n)-th order statistic.
                return lat[min(n - 1, max(0, math.ceil(p * n) - 1))]

            return {
                "filterCount": self.filter_count,
                "filterLatencyP50Ms": pct(0.50) * 1e3,
                "filterLatencyP99Ms": pct(0.99) * 1e3,
                "bindCount": self.bind_count,
                "preemptCount": self.preempt_count,
                "waitCount": self.wait_count,
                "bindRetryCount": self.bind_retry_count,
                "bindGiveUpCount": self.bind_give_up_count,
                "bindTerminalFailureCount": self.bind_terminal_count,
                "quarantineCount": self.quarantine_count,
                "requestDeadlineExceededCount": (
                    self.request_deadline_exceeded_count
                ),
                "doomedLedgerPersistCount": self.ledger_persist_count,
                "doomedLedgerPersistFailureCount": (
                    self.ledger_persist_failure_count
                ),
                "preemptionRecoveredCount": self.preemption_recovered_count,
                "preemptionCancelledOnRecoveryCount": (
                    self.preemption_cancelled_on_recovery_count
                ),
                "healthTransitionCount": self.health_transition_count,
                "healthDampedCount": self.health_damped_count,
                "healthSettledCount": self.health_settled_count,
                "nodeEventNoopCount": self.node_event_noop_count,
                "fastWaitCount": self.fast_wait_count,
                "doomedLedgerCoalescedCount": self.ledger_coalesced_count,
                "strandedEvictionCount": self.stranded_eviction_count,
                "snapshotPersistCount": self.snapshot_persist_count,
                "snapshotPersistFailureCount": (
                    self.snapshot_persist_failure_count
                ),
                "snapshotFallbackCount": self.snapshot_fallback_count,
                "snapshotSectionFallbackCount": (
                    self.snapshot_section_fallback_count
                ),
                "deposedBindRefusedCount": self.deposed_bind_refused_count,
                "outageBindRefusedCount": self.outage_bind_refused_count,
                "outageWaitCount": self.outage_wait_count,
                "gangShrinkCount": self.gang_shrink_count,
                "gangShrinkAbortCount": self.gang_shrink_abort_count,
                "gangGrowCount": self.gang_grow_count,
                "defragProposalCount": self.defrag_proposal_count,
                "defragMigrationCount": self.defrag_migration_count,
                "defragCancelCount": self.defrag_cancel_count,
                "phases": self.phase_stats.snapshot(),
                "latencyHistograms": {
                    "filter": self.hist_filter.snapshot(),
                    "preempt": self.hist_preempt.snapshot(),
                    "bind": self.hist_bind.snapshot(),
                    "recoveryReplay": self.hist_recovery_replay.snapshot(),
                },
            }


# A/B escape hatch (bench_relist_ab, doc/hot-path.md "Warehouse-scale
# profile"): =0 disables the node-event no-op fast path so every relist
# re-delivery takes the global lock order, the pre-fast-path behavior.
NODE_EVENT_FASTPATH_DEFAULT = (
    os.environ.get("HIVED_NODE_EVENT_FASTPATH", "") != "0"
)

# Pending-pod plane escape hatch (doc/hot-path.md "Pending-pod plane"):
# HIVED_WAIT_CACHE=0 disables the negative-filter cache so every
# re-filter of a waiting pod runs the full pass — the differential
# reference for the cached ≡ recomputed proof (tests/test_wait_cache.py).
# Read at construction (not import) so bench A/Bs can flip it per
# scheduler instance.
WAIT_CACHE_ENV = "HIVED_WAIT_CACHE"

# Shadow what-if plane metric keys (doc/observability.md): always present
# in get_metrics so the golden metrics schema holds before the plane's
# lazy construction. WhatIfPlane.metrics_snapshot emits the same keys;
# whatifForkAgeSeconds is -1 until a fork has been built (a staleness
# gauge must not read as "perfectly fresh" when no fork exists).
WHATIF_EMPTY_METRICS = {
    "whatifForecastCount": 0,
    "whatifForecastGangCount": 0,
    "whatifForkCount": 0,
    "whatifAuditViolationCount": 0,
    "whatifForkPodCount": 0,
    "whatifForkAgeSeconds": -1.0,
    "whatifForecastSeconds": 0.0,
}

# Black-box plane metric keys (doc/observability.md): always present in
# get_metrics so the golden metrics schema holds with the auditor or
# recorder disabled.
BLACKBOX_EMPTY_METRICS = {
    "auditRunCount": 0,
    "auditViolationCount": 0,
    "flightRecorderEventCount": 0,
    "flightRecorderReanchorCount": 0,
}

# Durable-state plane v2 scrubber keys (doc/observability.md): always
# present so the golden metrics schema holds with the scrubber disabled
# (HIVED_SNAPSHOT_SCRUB=0 or no operator wiring).
SCRUB_EMPTY_METRICS = {
    "scrubRunCount": 0,
    "scrubDivergenceCount": 0,
    "scrubRepairCount": 0,
}


class HivedScheduler:
    """(reference: pkg/scheduler/scheduler.go:53-120)"""

    def __init__(
        self,
        config: Config,
        kube_client: Optional[KubeClient] = None,
        # Injectable executor for force binds; the default spawns a thread the
        # way the reference spawns a goroutine (scheduler.go:505,533). Tests
        # pass a synchronous executor for determinism.
        force_bind_executor: Optional[Callable[[Callable[[], None]], None]] = None,
        # Standalone/simulation mode: admit never-informed pods at filter
        # time instead of relying on an informer to deliver them first
        # (production keeps the reference behavior: reject and let the
        # default scheduler retry after the informer catches up).
        auto_admit: bool = False,
        # Lock-sharding escape hatch: True forces every section to the
        # single-lock (all-chains) behavior for differential testing;
        # None reads HIVED_GLOBAL_LOCK (locks.ChainShardedLock).
        global_lock: Optional[bool] = None,
        # Tracing sample-rate override; None reads HIVED_TRACE_SAMPLE
        # (default 0.01). The bench A/B passes explicit values.
        trace_sample: Optional[float] = None,
        # Black-box plane overrides (doc/observability.md): False forces
        # the flight recorder / live auditor OFF regardless of config and
        # env — shadow forks and replay subjects must not record or audit
        # themselves recording. None reads config + env (the default).
        flight_recorder: Optional[bool] = None,
        live_audit: Optional[bool] = None,
    ) -> None:
        self.config = config
        self.kube_client = kube_client or NullKubeClient()
        self.core = HivedCore(config)
        self.metrics = SchedulerMetrics()
        # Observability plane (doc/observability.md): the span tracer (ring
        # of sampled request traces), and the always-on decision journal
        # (per-attempt gate rejections + verdicts, /v1/inspect/decisions).
        self.tracer = tracing.Tracer(
            sample=trace_sample, capacity=config.trace_ring_capacity
        )
        self.decisions = DecisionJournal(
            capacity=config.decision_journal_capacity
        )
        self.core.decisions = self.decisions
        # Pending-pod plane (doc/hot-path.md "Pending-pod plane"): the
        # negative-filter cache. Keyed by spec identity (the raw
        # scheduling-spec annotation text — every pod of a gang shares
        # it), each entry memoizes a WAIT verdict plus its rejection
        # certificate; a re-filter whose version vector is unchanged is
        # answered without a lock section or placement descent
        # (_try_fast_wait). Bounded FIFO (wait_cache_capacity); reads are
        # lock-free GIL-atomic dict gets, writes take the micro-lock
        # below (never held while acquiring anything else). Cleared
        # wholesale around snapshot restores — restore_projection writes
        # cell fields directly, without the epoch-bumping mutator hooks
        # the certificates rely on.
        self.wait_cache_enabled = (
            os.environ.get(WAIT_CACHE_ENV, "1").strip() != "0"
            and config.wait_cache_capacity > 0
        )
        self._wait_cache: Dict[str, Dict] = {}
        self._wait_cache_lock = threading.Lock()
        # Single-slot suggested-set token memo, validated by list-object
        # IDENTITY (the entry holds a strong reference, so the id cannot
        # recycle). Callers reusing one node-name list across filter
        # calls (the sim driver, the shards filter_fast memo) tokenize in
        # O(1); callers building a fresh list per request (the webserver)
        # pay one O(n) hash — still far below the set build they already
        # do. Contract: node-name lists handed to filter_routine are
        # never mutated in place (true for every caller today).
        self._suggested_token_memo: Optional[Tuple] = None
        # Scheduling serializes per cell chain (scheduler.locks): filter /
        # bind / preempt acquire only the chains their pod's spec can touch,
        # whole-cluster mutators (node/pod events, health, recovery,
        # inspect) take the global order — which is what self._lock now IS:
        # a guard over every chain lock, in total order, preserving the old
        # single-lock semantics for everything that still uses it
        # (reference: one lock, scheduler.go:104-108).
        self._locks = ChainShardedLock(
            self.core.full_cell_list.keys(), force_global=global_lock
        )
        self._lock = self._locks.global_guard
        # Runtime teeth of the sharding contract: cross-chain core mutators
        # assert the global order (see locks.require_global and the chaos
        # sensitivity meta-test).
        self.core.lock_validator = self._locks.require_global
        # Innermost mutex for the deferred-side-effect queues below
        # (annotation clears, evictions): they are appended to from inside
        # chain sections and swapped out by concurrent flushes, so the
        # global guard no longer covers them. Never held while acquiring
        # anything else.
        self._side_effect_lock = threading.Lock()
        # Mixed-SKU gang guard (see _claim_group_chains): group name ->
        # the chain-lock set its first not-yet-registered scheduler ran
        # under. Guarded by _side_effect_lock; entries die when the group
        # registers or its pods are deleted.
        self._group_chain_claims: Dict[str, Tuple[str, ...]] = {}
        # uid -> PodScheduleStatus for all live hived pods
        # (reference: scheduler.go:110-115).
        self.pod_schedule_statuses: Dict[str, PodScheduleStatus] = {}
        # Node cache standing in for the node lister (used by
        # validate_pod_bind_info; reference: scheduler.go:385-421).
        self.nodes: Dict[str, Node] = {}
        # uid -> QuarantineRecord: bound pods whose recovery replay failed
        # (corrupt bind info, cells gone from the config). Parked instead of
        # aborting recovery; surfaced via /v1/inspect/quarantine.
        self.quarantined_pods: Dict[str, QuarantineRecord] = {}
        # Readiness gate: /readyz stays 503 until recovery (the initial
        # list replay) completes, mirroring the reference's WaitForCacheSync
        # ordering (scheduler.go:200-212).
        self._ready = threading.Event()
        self.auto_admit = auto_admit
        if auto_admit:
            # Standalone/simulation mode has no recovery phase.
            self._ready.set()
        self._spawn = force_bind_executor or self._default_executor
        # Preempt/reconfig fault plane (doc/fault-model.md): deferred kube
        # side effects collected under the lock and flushed when the
        # OUTERMOST mutator exits (network writes never run under the
        # scheduler lock). _mutation_depth is per-thread because mutators
        # nest (update_pod -> delete_pod+add_pod, recover -> everything).
        self._mutation_depth = threading.local()
        self._pending_annotation_clears: List[Pod] = []
        self._persisted_doomed_epoch = -1
        self._ledger_write_lock = threading.Lock()
        self.core.preemption_observer = self._on_preemption_event
        # Hardware health plane (doc/fault-model.md "Hardware health
        # plane"): node/chip health observations pass through an
        # event-clocked flap damper before touching the core, so a flapping
        # node settles instead of storming doom churn and ledger rewrites.
        # Drains apply undamped (deliberate operator actions).
        self._health_clock = 0
        self.node_event_fastpath = NODE_EVENT_FASTPATH_DEFAULT
        # Last-APPLIED health projection per node (written by the locked
        # node-event paths, popped on delete): the no-op fast path
        # compares one freshly computed projection against this cache
        # instead of re-parsing the stored node's annotations per event —
        # at fleet scale the relist re-delivers every node, so halving
        # the projection work halves the whole fast-path cost.
        self._node_projections: Dict[str, Tuple] = {}
        self._damper = health_mod.FlapDamper(
            config.health_flap_threshold,
            config.health_flap_window,
            config.health_flap_hold,
            hold_seconds=config.health_flap_hold_seconds,
        )
        # Per-node chip targets the damper has ever been told about, so a
        # chip dropping OUT of the device-health annotation is observed as
        # a heal rather than silently forgotten.
        self._chip_targets: Dict[str, set] = {}
        # Stranded-gang remediation: groups already evicted (never evict a
        # gang twice), the pod uids whose delete already landed (a partial
        # failure re-arms the gang but must not re-delete these), and the
        # pods queued for eviction, flushed outside the lock at mutator
        # exit like every other kube write.
        self._evicted_groups: set = set()
        self._evicted_pod_uids: set = set()
        self._pending_evictions: List = []
        # Elastic gang plane (doc/fault-model.md "Elastic gang plane"):
        # shrink plans queued by stranded remediation (flushed — survivor
        # annotation patches first, then the core reshape, then the
        # dropped members' evictions — at mutator exit, outside every
        # lock), and the groups with a plan in flight (never two plans
        # for one gang).
        self._pending_shrinks: List[Dict] = []
        self._shrink_in_flight: set = set()
        # True once a resize-related annotation write FAILED (shrink
        # rollback or stale-generation re-sync): the one window where
        # live pods legitimately carry bind-info generations that differ
        # from their group's (the chaos harness treats a crash inside it
        # as degraded instead of asserting strict equivalence).
        self._resize_write_failed = False
        # Background defragmenter (scheduler.defrag), armed by the
        # defragEnable knob; ticks on the health event clock.
        self.defrag = (
            DefragController(self) if config.defrag_enable else None
        )
        # Set when an eviction write failed: the next mutator-exit flush
        # re-runs the stranded check so the retry does not have to wait
        # for another health transition (which may never come on a quiet
        # cluster).
        self._eviction_retry_pending = False
        # Names of currently-stranded gangs, refreshed under the lock at
        # every applied health transition (_check_stranded_locked) and at
        # recovery end. The lock-free metrics scrape intersects it with
        # the live group set — groups whose pods died since the last
        # refresh drop out without a walk (doc/observability.md).
        self._stranded_names: set = set()
        # HA / snapshot recovery plane (doc/fault-model.md "HA and snapshot
        # recovery plane"). The compiled-config fingerprint stamps every
        # snapshot (a reconfiguration invalidates cell addresses); the
        # watermark is the informer's last-seen resourceVersion (or the
        # harness's event index), carried in the snapshot so recovery knows
        # the delta floor. _snapshot_pending holds imported-but-unconfirmed
        # pod fingerprints DURING recovery only: the delta replay pops each
        # as the live list confirms it, and finish_recovery releases the
        # leftovers (pods deleted while we were down). Always empty in
        # steady state.
        _t_fp = time.monotonic()
        self._config_fingerprint = snapshot_mod.config_fingerprint(config)
        self.core.boot_phase_seconds["fingerprint"] = (
            time.monotonic() - _t_fp
        )
        self._watermark = 0
        self._recovery_ledger: Optional[Dict] = None
        self._snapshot_pending: Dict[str, Tuple] = {}
        # Warm-standby decode cache: (chunk family, decoded body) of the
        # last prefetched snapshot (see prefetch_snapshot). When the
        # standby also PRE-APPLIED the projection into its core (hot
        # standby), _preapplied_chunks names the chunk family the live
        # state corresponds to, so takeover can skip the restore and run
        # only the delta replay.
        self._prefetched_snapshot: Optional[Tuple[List[str], Dict]] = None
        self._preapplied_chunks: Optional[List[str]] = None
        # Non-None when the pre-apply was PARTIAL (corrupt sections in
        # the prefetched envelope): the chain set the standby left in
        # bootstrap state for the takeover's scoped annotation replay.
        # Takeover trusts the pre-apply only if its own gate (run against
        # the real crash ledger) demotes exactly the same chains.
        self._preapplied_replay: Optional[frozenset] = None
        self._last_snapshot_chunks: Optional[List[str]] = None
        # Imported pods released mid-replay by a claim conflict: their live
        # events may already have been visited, so finish_recovery re-adds
        # any that are still live (full replay admits them; losing them
        # until the next relist would not be equivalent).
        self._snapshot_released_uids: Set[str] = set()
        # (chain, node, leaf-index) -> importing pod uid, for conflict
        # detection during the delta replay (entries for since-confirmed
        # pods go stale and are ignored via the pending-map check).
        self._snapshot_claims: Dict[Tuple, str] = {}
        self._snapshot_imported_count = 0
        self._snapshot_delta_count = 0
        self._recovery_mode = "none"
        # True between begin_recovery and finish_recovery/_abort_recovery:
        # per-transition stranded-gang scans are suppressed while the
        # replay applies one transition per node (finish_recovery seeds
        # the gauge once at the end instead).
        self._in_recovery = False
        # Per-pod export-record memo for the flusher: a confirmed-BOUND
        # pod object is immutable (the informer replaces the object on any
        # change), so its serialized snapshot record is a pure function of
        # the object. Keyed by uid, validated by object IDENTITY — the
        # tuple keeps a strong reference to the pod, so the id can never
        # be recycled while the entry lives. Each entry carries both the
        # record dict (for the body) and its serialized JSON text (for the
        # encoder's section-assembly fast path). Rebuilt (and thereby
        # pruned) on every export walk.
        self._snapshot_pod_export_cache: Dict[
            str, Tuple[Pod, Dict, str]
        ] = {}
        self._snapshot_write_lock = threading.Lock()
        self._flusher_stop: Optional[threading.Event] = None
        self._flusher_thread: Optional[threading.Thread] = None
        # Max-staleness override (doc/fault-model.md "Durable-state plane
        # v2"): the flusher's export gate refuses while a PREEMPTING
        # group is live, so sustained preempt churn could starve
        # snapshots forever. When a refused flush finds the snapshot past
        # its staleness budget it raises the wanted flag; the next
        # mutation-bracket exit (a quiet point by construction) pokes the
        # flusher's wake event for an immediate retry instead of waiting
        # out the interval. _last_flush_monotonic feeds the
        # snapshotAgeSeconds gauge (-1 until the first flush); the age
        # anchor also arms at mark_ready so a leader that never managed a
        # single flush still trips the override.
        self._flusher_wake: Optional[threading.Event] = None
        self._snapshot_flush_wanted = False
        self._last_flush_monotonic: Optional[float] = None
        self._snapshot_age_anchor: Optional[float] = None
        # Continuous integrity scrubber (scheduler.scrub): constructed by
        # the operator wiring (__main__/ha), rides the flusher's beats.
        # None = scrubbing disabled (tests, simulators, the env hatch).
        self.scrubber = None
        # Leader-election gate (scheduler.ha.LeaderElector, or anything
        # with is_leader()). None = HA disabled: this process is always
        # the leader (single-scheduler deployments, tests, simulators).
        self.leadership = None
        self._deposed_flush_logged = False
        # Control-plane weather plane (doc/fault-model.md "Control-plane
        # weather plane"): the apiserver outage detector and the
        # write-behind intent journal. RetryingKubeClient (scheduler.kube)
        # wires itself to both when constructed with scheduler=self: it
        # feeds every attempt outcome to the vane, journals durable writes
        # that exhaust retries under blackout, and drains the journal from
        # the mutator-exit flush once the weather clears and leadership is
        # re-confirmed (_flush_side_effects).
        self.weather_vane = weather_mod.WeatherVane(
            window=getattr(config, "weather_window", 32),
            blackout_after=getattr(config, "weather_blackout_after", 8),
            clear_after=getattr(config, "weather_clear_after", 3),
        )
        self.intent_journal = weather_mod.IntentJournal(
            capacity=getattr(config, "intent_journal_capacity", 512)
        )
        # Shadow what-if plane (scheduler.whatif): constructed lazily by
        # the first whatif_routine call (or by the bench's sim sampler),
        # under _whatif_init_lock — two racing first POSTs on the
        # threading webserver must not build two planes (separate
        # serialization locks, and each re-arms the audit to ITS
        # thread-locals, silently disarming the other's). _mutation_guard
        # is the framework half of the read-only-fork audit — armed by
        # the plane, None (zero overhead) otherwise.
        self._whatif = None
        self._whatif_init_lock = threading.Lock()
        self._mutation_guard: Optional[Callable[[], None]] = None
        # Black-box plane (doc/observability.md "The black-box plane"):
        # the production flight recorder (bounded verb ring, anchored on
        # the fork-body snapshot export + preempt-RNG state, replayable
        # via `python -m hivedscheduler_tpu.sim --replay-recording`) and
        # the live invariant auditor (tests/chaos.py's audit_invariants,
        # one implementation, run event-clocked under a brief global
        # section — violations count + journal + dump, never assert).
        self.recorder: Optional[recorder_mod.FlightRecorder] = None
        if (
            flight_recorder is not False
            and config.flight_recorder_capacity > 0
            and os.environ.get(
                recorder_mod.FLIGHT_RECORDER_ENV, "1"
            ).strip() != "0"
        ):
            self.recorder = recorder_mod.FlightRecorder(
                capacity=config.flight_recorder_capacity,
                exporter=self.export_fork_body,
                rng_state_fn=lambda: (
                    self.core.preempt_rng.getstate()
                    if self.core.preempt_rng is not None
                    else None
                ),
                config_fingerprint=self._config_fingerprint,
                granularity="framework",
            )
            self.recorder.set_node_universe(
                self.core.configured_node_names()
            )
        self.live_auditor: Optional[audit_mod.LiveAuditor] = None
        if (
            live_audit is not False
            and config.audit_interval_ticks > 0
            and os.environ.get(
                audit_mod.LIVE_AUDIT_ENV, "1"
            ).strip() != "0"
        ):
            if not __debug__:
                # audit_invariants is assert-built (one implementation
                # shared with the chaos harness); python -O strips
                # asserts, which would leave an auditor that "runs" and
                # catches nothing. Refuse to arm instead — buildInfo
                # then honestly reports liveAudit=off and the run
                # counter stays 0, rather than climbing while blind.
                common.log.warning(
                    "live invariant auditor DISABLED: running under "
                    "python -O strips the audit asserts; re-run without "
                    "optimization to arm the black-box auditor"
                )
            else:
                self.live_auditor = audit_mod.LiveAuditor(
                    self, config.audit_interval_ticks
                )

    # ------------------------------------------------------------------ #
    # Black-box plane helpers (recorder hooks + auditor event clock)
    # ------------------------------------------------------------------ #

    def _blackbox_top(self) -> bool:
        """True when the CURRENT verb entry is top-level (not nested in
        another mutator): only top-level verbs are recorded — a nested
        delete+add inside update_pod is the update's implementation, and
        recording both would double-apply on replay."""
        return getattr(self._mutation_depth, "d", 0) == 0

    def _blackbox_tick(self) -> None:
        """The auditor's event clock: called OUTSIDE every lock at
        top-level verb exit (never from paths that may hold a chain
        section, e.g. the sync force-bind re-entry)."""
        aud = self.live_auditor
        if aud is not None:
            aud.tick()

    def _blackbox_record_preempt(self, args, result) -> None:
        """The shared preempt-verb capture (recorder.record_preempt_result
        — one classification for both frontends), never raising."""
        rec = self.recorder
        if rec is None:
            return
        try:
            recorder_mod.record_preempt_result(rec, args.pod, args, result)
        except Exception:  # noqa: BLE001
            common.log.exception("flight-recorder hook failed")

    def _blackbox_record(self, method: str, *args, **kwargs) -> None:
        """One recorder hook, always AFTER the verb executed (so a
        re-anchor triggered by the append captures state that already
        subsumes the event — dropping it from the fresh window is exact)
        and never raising into the serving path."""
        rec = self.recorder
        if rec is None:
            return
        try:
            getattr(rec, method)(*args, **kwargs)
        except Exception:  # noqa: BLE001
            common.log.exception("flight-recorder hook failed")

    @staticmethod
    def _fault_kind_from_projections(prev, cur) -> str:
        """The chaos-vocabulary fault kind a node-event projection diff
        corresponds to (recorded on node_state events as diagnostic
        context for the sim tier's wake semantics)."""
        if prev is None or cur is None:
            return ""
        pready, pbad, pdrain = prev
        cready, cbad, cdrain = cur
        if pready != cready:
            return "node_flip"
        if cbad - pbad:
            return "chip_fault"
        if pbad - cbad:
            return "chip_heal"
        if pdrain != cdrain:
            return "drain_toggle"
        return ""

    @staticmethod
    def _default_executor(fn: Callable[[], None]) -> None:
        threading.Thread(target=fn, daemon=True).start()

    # ------------------------------------------------------------------ #
    # Lock sharding (scheduler.locks; doc/hot-path.md "The lock-sharding
    # contract")
    # ------------------------------------------------------------------ #

    def _pod_lock_chains(
        self, pod: Pod, spec: Optional[api.PodSchedulingSpec] = None
    ) -> Optional[List[str]]:
        """The cell chains a scheduling call for this pod can touch,
        derived from the spec BEFORE lock acquisition: the chains carrying
        the requested leaf SKU (or the pinned cell's chain; or, for a
        GUARANTEED pod without a leafCellType, the chains its VC holds
        non-pinned quota in — any-leaf-type scheduling only probes chains
        passing that quota gate, core.vc_quota_chains), widened by the
        chain its existing affinity group is placed in. None means "cannot
        be narrowed" (no/undecodable spec, or an untyped OPPORTUNISTIC pod
        — those probe every chain) and degrades to the global order. Reads
        only compile-time config plus atomic dict lookups, so it is safe
        without locks; the caller re-derives INSIDE the section
        (_run_chain_locked) to close the derive-then-acquire race."""
        if spec is None:
            try:
                spec = extract_pod_scheduling_spec(pod)
            except api.WebServerError:
                return None
        core = self.core
        chains: Optional[List[str]] = None
        if spec.pinned_cell_id:
            # Compile-metadata lookup (never forces a lazy VC compile —
            # this derivation runs lock-free): the pinned cell's chain is
            # its physical cell's.
            pinned = core.compiled.physical_pinned.get(
                spec.virtual_cluster, {}
            ).get(spec.pinned_cell_id)
            if pinned is None:
                return None  # unknown pinned cell: validation rejects inside
            chains = [pinned.chain]
        elif spec.leaf_cell_type:
            typed = core.cell_chains.get(spec.leaf_cell_type)
            if not typed:
                return None  # unknown SKU: schedule() rejects inside
            chains = list(typed)
        elif spec.priority >= constants.MIN_GUARANTEED_PRIORITY:
            # Untyped guaranteed pod: _schedule_group_for_any_leaf_type
            # gates every chain on membership in the VC's non-pinned
            # quota, so that quota set IS the reachable chain set.
            quota_chains = core.vc_quota_chains(spec.virtual_cluster)
            if not quota_chains:
                return None  # unknown VC / no quota: rejected inside
            chains = list(quota_chains)
        else:
            # Untyped opportunistic pod: probes every chain.
            return None
        g = core.affinity_groups.get(spec.affinity_group.name)
        if g is not None:
            gchain = group_chain(g)
            if gchain is not None and gchain not in chains:
                # A gang pod whose leaf type differs from the pod that
                # placed the group: its group state lives elsewhere.
                chains.append(gchain)
        if pod.node_name:
            # Bound pod (replay / lifecycle event): its cells are on its
            # node, and the node -> leaf index is compile-time static, so
            # this is exact even when a reconfiguration moved the node to
            # a chain outside the spec's SKU set (the moved-cell fallback
            # in find_physical_leaf_cell searches every chain).
            for leaf in core._node_leaf_index.get(pod.node_name, []):
                if leaf.chain not in chains:
                    chains.append(leaf.chain)
        return chains

    def _claim_group_chains(self, spec, keys: Tuple[str, ...]) -> bool:
        """Guard against the mixed-SKU gang race: two pods of ONE gang
        whose specs derive disjoint chain sets (different leafCellType —
        pathological but legal input) could otherwise schedule the
        not-yet-registered group concurrently under different locks and
        double-create it (the loser's cells would leak on an orphaned
        group object). The first scheduler of an unregistered group claims
        the name with its lock set; a claim COVERED by the current keys is
        provably finished (a live claimant would still hold those locks,
        which we now hold) and is overridden, while an uncovered claim may
        still be running — the caller degrades to the global order.
        Claims die when the group registers or its pods are deleted."""
        if spec is None or spec.affinity_group is None:
            return True
        name = spec.affinity_group.name
        if self.core.affinity_groups.get(name) is not None:
            # Registered: group existence itself now serializes (its chain
            # is in every pod's lock set via _pod_lock_chains).
            with self._side_effect_lock:
                self._group_chain_claims.pop(name, None)
            return True
        with self._side_effect_lock:
            cur = self._group_chain_claims.get(name)
            if cur is not None and not set(cur).issubset(keys):
                return False
            self._group_chain_claims[name] = tuple(keys)
        return True

    def _drop_group_claim(self, name: Optional[str]) -> None:
        if name:
            with self._side_effect_lock:
                self._group_chain_claims.pop(name, None)

    def _run_chain_locked(self, pod, spec, fn):
        """Run ``fn(section)`` under the pod's chain locks. The needed set
        is re-derived inside the section and the section retried wider if
        it moved (another pod of the gang can register the group in a chain
        outside this pod's spec-derived set between derivation and
        acquisition), and an unregistered group's name must be claimable
        for this lock set (_claim_group_chains); bounded, then degrades to
        the global order. Lock wait of an abandoned too-narrow section is
        carried into the section that finally runs ``fn`` so the lockWait
        metric reports the true total."""
        if spec is None:
            try:
                spec = extract_pod_scheduling_spec(pod)
            except api.WebServerError:
                spec = None
        chains = self._pod_lock_chains(pod, spec)
        carried_wait = 0.0
        for _ in range(2):
            sec = self._locks.section(chains)
            with sec:
                if sec.keys == self._locks.all_keys:
                    # Global: covers everything; a stale uncovered claim
                    # must not keep degrading this gang's pods forever.
                    if spec is not None and spec.affinity_group is not None:
                        self._drop_group_claim(spec.affinity_group.name)
                    sec.wait_s += carried_wait
                    return fn(sec)
                needed = self._pod_lock_chains(pod, spec)
                ok = needed is not None and set(needed).issubset(sec.keys)
                if ok and not self._claim_group_chains(spec, sec.keys):
                    needed = None  # conflicting live claim: go global
                    ok = False
                if ok:
                    sec.wait_s += carried_wait
                    return fn(sec)
            carried_wait += sec.wait_s
            chains = needed
        sec = self._locks.section(None)
        with sec:
            if spec is not None and spec.affinity_group is not None:
                self._drop_group_claim(spec.affinity_group.name)
            sec.wait_s += carried_wait
            return fn(sec)

    # ------------------------------------------------------------------ #
    # Deferred kube side effects (preempt/reconfig fault plane)
    # ------------------------------------------------------------------ #

    def _enter_mutation(self) -> None:
        # Shadow what-if audit (scheduler.whatif): every framework verb
        # passes through here, so a shadow-forecast thread driving LIVE
        # verbs by mistake raises before any state moves (the core-level
        # write_guard fences direct core mutations the same way).
        guard = self._mutation_guard
        if guard is not None:
            guard()
        self._mutation_depth.d = getattr(self._mutation_depth, "d", 0) + 1

    def _exit_mutation(self) -> None:
        self._mutation_depth.d -= 1
        if self._mutation_depth.d == 0:
            self._flush_side_effects()
            if self._snapshot_flush_wanted:
                # Staleness override: a refused flush found the snapshot
                # past its budget; this quiet point is the flusher's
                # earliest legal retry (the export gate re-checks).
                wake = self._flusher_wake
                if wake is not None:
                    wake.set()

    def _on_preemption_event(self, group, event: str) -> None:
        """Core observer (called under the acting thread's chain section):
        a preempting group completed or was cancelled — its pods'
        preempt-info annotations are stale; clear them once the locks are
        released."""
        with self._side_effect_lock:
            self._pending_annotation_clears.extend(
                group.preempting_pods.values()
            )

    def _flush_side_effects(self) -> None:
        """Run the kube writes collected during the mutation that just
        ended: preempt-info annotation clears and the doomed-ledger
        ConfigMap. Both are ADVISORY (recovery fidelity, not correctness of
        the live view), so failures log and count — never raise into the
        scheduling path.

        A DEPOSED leader drops its queues instead of flushing: the new
        leader owns the cluster now, and a stale annotation clear or
        eviction could erase a checkpoint (or delete a pod) the new leader
        just placed. Dropping is safe — every queued write is advisory."""
        if not self.is_leader():
            with self._side_effect_lock:
                dropped = (
                    len(self._pending_annotation_clears)
                    + len(self._pending_evictions)
                    + len(self._pending_shrinks)
                )
                self._pending_annotation_clears = []
                self._pending_evictions = []
                self._shrink_in_flight -= {
                    p["group"] for p in self._pending_shrinks
                }
                self._pending_shrinks = []
            # Drain (and drop) the core's resize plumbing too: a standby
            # mirroring the leader replays every resize through
            # apply_resize, and without a drain the event/orphan lists
            # grow unboundedly, then fire as a burst of stale side
            # effects at promotion.
            self.core.take_resize_events()
            self.core.take_resize_orphans()
            if dropped and not self._deposed_flush_logged:
                self._deposed_flush_logged = True
                common.log.warning(
                    "deposed: dropping %d queued advisory kube writes (the "
                    "active leader owns the cluster state)", dropped,
                )
            # Intent-journal fence (doc/fault-model.md "Control-plane
            # weather plane"): DISCARD journaled intents only on DEFINITE
            # supersession — another holder observed on the lease. A
            # leader merely unable to renew through a blackout keeps its
            # journal: if its own identity is still on the lease when the
            # weather clears, it resumes leadership warm and drains; if a
            # standby took over meanwhile, the first post-heal election
            # step observes the new holder and this branch discards.
            if self._definitely_superseded():
                self.intent_journal.discard_all()
            return
        self._deposed_flush_logged = False
        self._flush_annotation_clears()
        self._flush_shrinks()
        self._drain_resize_side_effects()
        if self.defrag is not None:
            self.defrag.flush_patches()
        self._flush_evictions()
        if self._eviction_retry_pending:
            # A prior eviction (or shrink-patch) write failed: re-detect
            # and re-queue now (one retry round per flush — a
            # still-failing write re-sets the flag for the NEXT mutator
            # exit, so this cannot loop).
            with self._lock:
                self._eviction_retry_pending = False
                self._check_stranded_locked()
            self._flush_shrinks()
            self._drain_resize_side_effects()
            self._flush_evictions()
        self._persist_doomed_ledger()
        # Weather heal: replay journaled intents once the vane allows a
        # drain (clear skies / read class proven clear) — leadership was
        # just confirmed above. O(1) no-op while the journal is empty.
        drain = getattr(self.kube_client, "maybe_drain", None)
        if drain is not None and self.intent_journal.depth():
            try:
                drain()
            except Exception as e:  # noqa: BLE001
                common.log.warning("intent journal drain failed: %s", e)

    def _definitely_superseded(self) -> bool:
        """True only when the HA elector has OBSERVED another holder on
        the lease — the discard-vs-keep pivot for the intent journal. A
        lease merely unrenewable (apiserver unreachable) keeps the
        journal for the own-lease warm-resumption path (scheduler.ha)."""
        lead = self.leadership
        if lead is None:
            return False
        holder = getattr(lead, "observed_holder", None)
        identity = getattr(lead, "identity", None)
        return bool(holder) and holder != identity

    def _flush_annotation_clears(self) -> None:
        with self._side_effect_lock:
            clears, self._pending_annotation_clears = (
                self._pending_annotation_clears, []
            )
        for pod in clears:
            try:
                self.kube_client.patch_pod_annotations(
                    pod, {constants.ANNOTATION_POD_PREEMPT_INFO: None}
                )
            except Exception as e:  # noqa: BLE001
                common.log.warning(
                    "[%s]: clearing stale preempt-info annotation failed "
                    "(recovery tolerates stale annotations): %s", pod.key, e,
                )

    def _persist_doomed_ledger(self) -> None:
        """Write the advisory doomed-bad ledger to its scheduler-owned
        ConfigMap when it changed since the last successful write. The
        write runs outside the scheduler lock; _ledger_write_lock serializes
        concurrent flushes so snapshots cannot land out of order."""
        # LOCK-FREE fast path: a mutator that changed nothing doomed (the
        # overwhelmingly common case — every filter call ends here) must
        # neither block behind another thread's in-flight ConfigMap write
        # nor take the all-chains global order just to compare two ints
        # (int reads are atomic). Benign race: a stale read just means the
        # next flush (or the in-flight writer's re-snapshot) picks the
        # change up.
        if self.core.doomed_epoch == self._persisted_doomed_epoch:
            return
        with self._ledger_write_lock:
            with self._lock:
                epoch = self.core.doomed_epoch
                if epoch == self._persisted_doomed_epoch:
                    return
                snapshot = self.core.doomed_ledger_snapshot()
            try:
                self.kube_client.persist_scheduler_state(
                    common.to_json(snapshot)
                )
            except Exception as e:  # noqa: BLE001
                self.metrics.observe_ledger_persist(False)
                common.log.warning(
                    "doomed-ledger ConfigMap write failed (epoch %d; a "
                    "restart before the next successful write recovers "
                    "with a stale ledger): %s", epoch, e,
                )
                return
            self.metrics.observe_ledger_persist(True)
            if self._persisted_doomed_epoch >= 0:
                # N epoch bumps since the last landed write collapsed into
                # one ConfigMap write: the per-mutator flush (plus flap
                # damping upstream) is what keeps heavy node churn from
                # storming the apiserver with ledger rewrites.
                coalesced = epoch - self._persisted_doomed_epoch - 1
                if coalesced > 0:
                    self.metrics.observe_ledger_coalesced(coalesced)
            self._persisted_doomed_epoch = epoch

    def get_doomed_ledger(self) -> Dict:
        """Inspect payload for /v1/inspect/doomedledger: the live advisory
        doomed-bad bindings plus the persistence epochs (live vs last
        successfully written)."""
        with self._lock:
            snap = self.core.doomed_ledger_snapshot()
            snap["persistedEpoch"] = self._persisted_doomed_epoch
        return snap

    # ------------------------------------------------------------------ #
    # Recovery (reference: scheduler.go:196-216 Run)
    # ------------------------------------------------------------------ #

    def recover(
        self,
        nodes: Iterable[Node],
        pods: Iterable[Pod],
        min_watermark=None,
    ) -> None:
        """Replay the current cluster state before serving requests.

        O(delta) path (doc/fault-model.md "HA and snapshot recovery
        plane"): when a VALID persisted snapshot exists — schema version,
        checksum, config fingerprint, and watermark (not older than
        ``min_watermark``) all check out — its bound pods are imported in
        bulk through the decode-free admission path, and the live pod list
        then acts as the DELTA replay: an imported pod whose live
        annotations are unchanged confirms in O(1); a changed one replays
        from its annotations; a new one replays normally; imported pods
        absent from the live list are released at finish_recovery. Any
        snapshot problem — or a failure mid-import — falls back to the
        full annotation replay (snapshotFallbackCount), which is exactly
        the pre-snapshot behavior: every bound hived pod re-enters via
        add_pod -> add_bound_pod -> AddAllocatedPod.

        The persisted doomed ledger is loaded FIRST and installed as the
        core's doomed-cell preference map, so the advisory doomed-bad
        bindings reconstruct onto the same cells the pre-crash scheduler
        chose (doc/fault-model.md "Reconfiguration plane"). Preempting
        groups always replay from live preempt-info annotations (they are
        deltas by nature — the live annotation is fresher than any
        snapshot).

        Fault contract: one unreplayable pod must not abort recovery —
        add_pod quarantines bound pods whose annotations cannot be replayed
        (see _add_bound_pod); anything else escaping is caught here so the
        remaining pods still recover. Readiness (/readyz) flips only after
        the full replay."""
        pod_list = list(pods)
        node_list = list(nodes)
        # Recovery is rare and expensive: always trace it (force bypasses
        # the sampling knob) so the last boot's phase breakdown is in the
        # trace ring.
        tr = self.tracer.trace("recovery", force=True)
        ledger_payload = None
        with tr.span("ledgerLoad"):
            try:
                ledger_payload = self.kube_client.load_scheduler_state()
            except Exception as e:  # noqa: BLE001
                common.log.warning(
                    "doomed-ledger ConfigMap read failed; recovering without "
                    "it (advisory dooms re-derive arbitrarily): %s", e,
                )
        with tr.span("snapshotLoad"):
            snap = self.load_valid_snapshot(min_watermark)
        if snap is None:
            self.discard_preapplied_state()
        self.begin_recovery(
            ledger_payload, defer_doom_rebuild=snap is not None
        )
        try:
            if snap is not None:
                # BEFORE the node replay: the restore reinstates the
                # snapshot-time cell state (health included) wholesale, and
                # the node replay then acts as the health half of the delta
                # — an unchanged node's observation no-ops against the
                # restored records in O(chips), a changed one applies its
                # real transition.
                with tr.span("snapshotImport"):
                    self.import_snapshot(snap, node_list)
            # The replay loops run the add_node/add_pod LOCKED BODIES under
            # one global section instead of acquiring per event: recover()
            # is single-threaded, already inside the begin/finish mutation
            # bracket, and the global guard covers every chain — per-event
            # lock churn was a measurable slice of the recovery blackout at
            # fleet scale. (The informer boot path keeps the per-event
            # calls: it shares the process with live traffic.)
            with tr.span("nodeReplay"):
                n_nodes = 0
                with self._lock:
                    for node in node_list:
                        self.nodes[node.name] = node
                        self._observe_node_health(node)
                        n_nodes += 1
            with tr.span("podReplay", pods=len(pod_list)):
                with self._lock:
                    for pod in pod_list:
                        if not is_interested(pod):
                            continue
                        bound = is_bound(pod)
                        t0 = time.monotonic() if bound else 0.0
                        try:
                            if bound:
                                self._add_bound_pod_locked(pod)
                            else:
                                self._admit_unbound(pod)
                        except Exception as e:  # noqa: BLE001
                            self._quarantine_pod(pod, e)
                        if bound:
                            self.metrics.observe_recovery_replay(
                                time.monotonic() - t0
                            )
        except BaseException:
            self._abort_recovery()
            tr.finish(outcome="aborted")
            raise
        with tr.span("preemptReplay"):
            self.finish_recovery(pod_list)
        tr.finish(outcome="ok", nodes=n_nodes, mode=self._recovery_mode)

    def begin_recovery(
        self,
        ledger_payload: Optional[str],
        defer_doom_rebuild: bool = False,
    ) -> None:
        """Phase 1 of recovery, before the node/pod replay: install the
        persisted doomed ledger (authoritative when present — organic doom
        churn suspends and the doomed set rebuilds to exactly the ledger)
        and suspend side-effect flushes until finish_recovery. Paired with
        finish_recovery; the InformerLoop boot path brackets its initial
        relists with the two so it recovers identically to recover().

        ``defer_doom_rebuild`` is set by recover() when a validated
        snapshot is about to be imported: the verbatim restore carries the
        ledger's own dooms (import_snapshot's gate enforces exact
        equality), so rebuilding on the bootstrap state first would be
        wasted churn — the import runs the rebuild itself on the paths
        that still need it (fallbacks)."""
        self._enter_mutation()
        self._in_recovery = True
        self._recovery_t0 = time.monotonic()
        # The replay (and any snapshot import inside it) rewrites cell
        # state through paths that bypass the epoch-bumping mutators;
        # every memoized WAIT certificate is void.
        self._wait_cache_clear()
        ledger = None
        if ledger_payload:
            try:
                ledger = common.from_yaml(ledger_payload) or None
            except Exception as e:  # noqa: BLE001
                common.log.warning(
                    "doomed-ledger payload undecodable; recovering without "
                    "it: %s", e,
                )
        # Kept for the mid-import fallback path: _reset_for_full_replay
        # re-installs the same decoded ledger on its fresh core.
        self._recovery_ledger = ledger
        self._recovery_mode = "full"
        self.core.set_preferred_doomed(ledger)
        if not defer_doom_rebuild:
            # The constructor's all-nodes-bad bootstrap already bound
            # advisory dooms to arbitrary cells; rebuild the doomed set to
            # exactly the ledger's before any health or pod replay.
            self.core.rebuild_doomed_from_ledger()

    def finish_recovery(self, pods: List[Pod]) -> None:
        """Phase 2 of recovery, after the bound-pod replay: release
        snapshot-imported pods the live cluster no longer has, replay
        preempting groups from preempt-info annotations, drop the ledger
        preferences (steady-state doom choices must not keep preferring
        the pre-crash layout), flip readiness, and flush the recovered
        ledger to the ConfigMap (the recovered state is now canonical)."""
        try:
            self._readd_released_snapshot_pods(pods)
            self._drop_vanished_snapshot_pods()
            self._recover_preempting_pods(pods)
        finally:
            self.core.clear_preferred_doomed()
            self._in_recovery = False
            t0 = getattr(self, "_recovery_t0", None)
            if t0 is not None:
                self.core.boot_phase_seconds["recovery"] = (
                    time.monotonic() - t0
                )
            # Replayed gangs may sit on hardware that broke while we were
            # down: seed the stranded-gang gauge before serving scrapes.
            with self._lock:
                self._refresh_stranded_locked()
            self.mark_ready()
            self._exit_mutation()
            if self.recorder is not None:
                # The replay rewrote state outside the recorded verb
                # stream: the current window no longer replays — the next
                # recorded verb re-anchors on the recovered projection.
                self.recorder.force_reanchor()

    def _abort_recovery(self) -> None:
        """The replay between begin_recovery and finish_recovery raised:
        drop the ledger preferences and re-enable side-effect flushes
        WITHOUT flipping readiness or persisting anything — the caller
        propagates the failure (and the process restarts), exactly the
        pre-recovery contract."""
        self.core.clear_preferred_doomed()
        self._in_recovery = False
        # Bare depth decrement, not _exit_mutation: a half-replayed state
        # must not overwrite the ConfigMap ledger.
        self._mutation_depth.d -= 1

    def _recover_preempting_pods(self, pods: List[Pod]) -> None:
        """The Reserving/Reserved half of recovery: replay preempting
        affinity groups from preempt-info annotations on unbound pods.
        Bound pods are already replayed (their bind info supersedes any
        stale preempt info). A reservation that cannot be replayed is
        cancelled and its annotation cleared — the pod re-schedules fresh."""
        for pod in pods:
            if not is_interested(pod) or is_bound(pod):
                continue
            if not has_pod_preempt_info(pod):
                continue
            with self._lock:
                try:
                    recovered, reason = (
                        self.core.recover_preempting_affinity_group(pod)
                    )
                except Exception as e:  # noqa: BLE001
                    common.log.error(
                        "[%s]: preemption recovery raised; canceling the "
                        "reservation: %s", pod.key, e,
                    )
                    recovered, reason = False, str(e)
                if recovered:
                    self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                        pod=pod, pod_state=PodState.PREEMPTING
                    )
                    self.metrics.observe_preemption_recovery(True)
                else:
                    common.log.warning(
                        "[%s]: preemption not recovered (%s); clearing its "
                        "preempt-info annotation", pod.key, reason,
                    )
                    self.metrics.observe_preemption_recovery(False)
                    with self._side_effect_lock:
                        self._pending_annotation_clears.append(pod)

    def mark_ready(self) -> None:
        """Recovery (initial list replay) complete: /readyz turns 200."""
        if self._snapshot_age_anchor is None:
            self._snapshot_age_anchor = time.monotonic()
        self._ready.set()

    def is_ready(self) -> bool:
        return self._ready.is_set()

    # ------------------------------------------------------------------ #
    # Snapshot plane (doc/fault-model.md "HA and snapshot recovery plane")
    # ------------------------------------------------------------------ #

    def note_watermark(self, watermark) -> None:
        """Record the informer's resourceVersion high-water mark (or the
        harness's event index): snapshots carry it so recovery knows which
        deltas the snapshot already contains. Atomic assignment — safe from
        the informer threads without a lock."""
        self._watermark = watermark

    def export_snapshot(self) -> Optional[List[str]]:
        """Serialize the durable projection into persistable chunks (the
        scheduler.snapshot format). The walk runs under the global guard —
        it reads pod statuses and core state — but the JSON encode and
        the ConfigMap write happen OUTSIDE any lock (the PR-3 doomed-ledger
        flush pattern; the flusher never holds chain locks across I/O).
        None while recovery is still in progress (a half-replayed view must
        never overwrite a complete snapshot) or while the projection is not
        normalized (see _export_body_locked) — the previous snapshot stays
        current and the delta replay covers the gap."""
        with self._lock:
            if not self._ready.is_set():
                return None
            raw = self._export_sections_locked()
            if raw is None:
                return None
            watermark = self._watermark
        # Render + checksum outside the lock: section payloads reference
        # the core's memoized per-chain dumps, which are rebuilt (never
        # mutated) on epoch bumps — the same property the monolithic
        # encoder relied on.
        sections = [
            (name, chains, snapshot_mod.section_text(payload, texts))
            for name, chains, payload, texts in raw
        ]
        return snapshot_mod.encode_sections(
            sections, self._config_fingerprint, watermark
        )

    def export_fork_body(self) -> Optional[Dict]:
        """The durable projection as a plain body dict for a SHADOW FORK
        (scheduler.whatif) — the snapshot walk without the ConfigMap
        round-trip (no chunk encode, no checksum, no persistence). Two
        relaxations vs the flusher's export, both forecast-correct:
        BINDING pods (assume-bound, informer confirm still in flight)
        are included — the fork wants the ASSUMED state the next filter
        call would schedule against — and the confirmed-BOUND durability
        gate does not apply. A PREEMPTING group in flight still returns
        None (reservations have no projection section); the window is
        one preemption resolving, and the caller retries or serves the
        previous fork with an honest staleness stamp."""
        with self._lock:
            exported = self._export_body_locked(for_fork=True)
            if exported is None:
                return None
            body, _pods_json = exported
        return body

    def _export_pods_locked(
        self,
        for_fork: bool = False,
    ) -> Optional[Tuple[List[Dict], List[str]]]:
        """The pod half of the durable projection — the confirmed-BOUND
        pods with their decoded spec/bind-info and slot index (so import
        can slot them without decoding) — as parallel record/serialized
        lists, plus the export GATE both snapshot layouts share.

        Returns None — skip this flush — while the projection carries
        transient overlays a real crash would forget: a PREEMPTING group
        (its Reserving/Reserved cells replay from live preempt-info
        annotations, never from snapshots) or an ALLOCATED group none of
        whose pods has confirmed BOUND (an assume-bind in flight — the
        bind write may still fail, and a real crash forgets it). Both
        windows are short (a preemption resolving, an informer confirm in
        flight); the flusher simply lands the snapshot on its next beat."""
        statuses = self.pod_schedule_statuses
        # Fork exports (scheduler.whatif) accept the ASSUMED state —
        # BINDING counts as confirmed and is exported below.
        durable_states = (
            (PodState.BOUND, PodState.BINDING)
            if for_fork
            else (PodState.BOUND,)
        )
        for g in self.core.affinity_groups.values():
            if g.state != GroupState.ALLOCATED:
                return None
            confirmed = False
            for slots in g.allocated_pods.values():
                for p in slots:
                    if p is None:
                        continue
                    st = statuses.get(p.uid)
                    if st is not None and st.pod_state in durable_states:
                        confirmed = True
                        break
                if confirmed:
                    break
            if not confirmed:
                return None
        iso = constants.ANNOTATION_POD_LEAF_CELL_ISOLATION
        pods_out: List[Dict] = []
        pods_json: List[str] = []
        # The per-pod export memo is the FLUSHER's: fork exports bypass it
        # both ways (a BINDING pod's record must never seed the durable
        # flusher cache, and fork walks are rare next to flushes).
        record_cache = (
            {} if for_fork else self._snapshot_pod_export_cache
        )
        new_cache: Dict[str, Tuple[Pod, Dict, str]] = {}
        for uid in sorted(self.pod_schedule_statuses):
            status = self.pod_schedule_statuses[uid]
            if status.pod_state not in durable_states:
                continue
            pod = status.pod
            cached = record_cache.get(uid)
            if cached is not None and cached[0] is pod:
                # Same immutable pod object as the last flush: its record
                # (and serialized text) cannot have changed — the
                # flusher's dominant cost at steady state was re-decoding
                # and re-dumping bind infos that never change (see
                # doc/hot-path.md).
                new_cache[uid] = cached
                pods_out.append(cached[1])
                pods_json.append(cached[2])
                continue
            try:
                spec = extract_pod_scheduling_spec(pod)
                info = extract_pod_bind_info(pod)
            except api.WebServerError:
                # Unreplayable annotations: leave the pod out — recovery
                # will quarantine it from the live annotations, exactly as
                # full replay would.
                continue
            record = {
                "name": pod.name,
                "namespace": pod.namespace,
                "uid": pod.uid,
                "node": pod.node_name,
                "phase": pod.phase,
                "resourceLimits": dict(pod.resource_limits),
                "annotations": {
                    k: v
                    for k, v in pod.annotations.items()
                    if k
                    in (
                        constants.ANNOTATION_POD_SCHEDULING_SPEC,
                        constants.ANNOTATION_POD_BIND_INFO,
                        iso,
                        constants.ANNOTATION_POD_TPU_ENV,
                    )
                },
                "spec": spec.to_dict(),
                "bindInfo": info.to_dict(),
                "podIndex": get_allocated_pod_index(
                    info, spec.leaf_cell_number
                ),
            }
            pods_out.append(record)
            if not for_fork:
                # The serialized text exists for the encoder's section
                # assembly; a fork consumes the record DICTS directly, so
                # serializing (under the lock, per fork, at fleet scale)
                # would be pure waste.
                record_text = json.dumps(record, separators=(",", ":"))
                new_cache[uid] = (pod, record, record_text)
                pods_json.append(record_text)
        if not for_fork:
            self._snapshot_pod_export_cache = new_cache
        # No "preempting" section: import never reads one (preempting
        # groups always replay from live preempt-info annotations — they
        # are deltas by nature), and the ALLOCATED-only gate above means
        # a flush can never coexist with a PREEMPTING group anyway.
        return pods_out, pods_json

    def _export_body_locked(
        self,
        for_fork: bool = False,
    ) -> Optional[Tuple[Dict, List[str]]]:
        """The durable projection as ONE MERGED body, exactly the state
        the chaos harness proves restart-equivalent: the core's verbatim
        cell-level projection (free/bad-free/doomed listings, sparse cell
        records, quota counters, allocated groups) plus the bound pods,
        the applied health records, and the doomed-ledger epoch. Used by
        fork exports (scheduler.whatif) and anywhere a monolithic body is
        still the right shape; the flusher exports per-family SECTIONS
        instead (_export_sections_locked)."""
        exported = self._export_pods_locked(for_fork)
        if exported is None:
            return None
        pods_out, pods_json = exported
        body = {
            "doomedEpoch": self.core.doomed_epoch,
            "health": self.core.health_snapshot(),
            "core": self.core.export_projection(),
            "pods": pods_out,
        }
        return body, pods_json

    def _export_sections_locked(
        self,
    ) -> Optional[List[Tuple[str, Optional[List[str]], Dict, Optional[List[str]]]]]:
        """The durable projection as PER-CHAIN-FAMILY sections (schema
        v3, doc/fault-model.md "Durable-state plane v2"): one section per
        compiled chain family — its merged projection slice plus the
        bound pods whose bind chain belongs to it — alongside the
        load-bearing ``meta`` (doomed epoch, chain-less groups, orphan
        pods) and ``health`` sections. Returns raw ``(name, chains,
        payload, pods_json)`` tuples; the caller renders and checksums
        OUTSIDE the lock. None = the export gate refused (see
        _export_pods_locked)."""
        exported = self._export_pods_locked(False)
        if exported is None:
            return None
        pods_out, pods_json = exported
        fams, chainless = self.core.export_projection_sections()
        fam_of_chain: Dict[str, int] = {}
        for i, fam in enumerate(fams):
            for c in fam["chains"]:
                fam_of_chain[str(c)] = i
        fam_recs: List[List[Dict]] = [[] for _ in fams]
        fam_texts: List[List[str]] = [[] for _ in fams]
        orphan_recs: List[Dict] = []
        orphan_texts: List[str] = []
        for rec, text in zip(pods_out, pods_json):
            i = fam_of_chain.get(str(rec["bindInfo"]["cellChain"]))
            if i is None:
                # A bind chain no compiled family covers (unreachable in
                # steady state — bind infos validate against the config):
                # rides the meta section, replayed like chain-less state.
                orphan_recs.append(rec)
                orphan_texts.append(text)
            else:
                fam_recs[i].append(rec)
                fam_texts[i].append(text)
        sections: List[
            Tuple[str, Optional[List[str]], Dict, Optional[List[str]]]
        ] = [
            (
                snapshot_mod.SECTION_META,
                None,
                {
                    "doomedEpoch": self.core.doomed_epoch,
                    "groups": chainless,
                    "pods": orphan_recs,
                },
                orphan_texts,
            ),
            (
                snapshot_mod.SECTION_HEALTH,
                None,
                self.core.health_snapshot(),
                None,
            ),
        ]
        for i, fam in enumerate(fams):
            sections.append((
                f"family:{i}",
                list(fam["chains"]),
                {"core": fam["core"], "pods": fam_recs[i]},
                fam_texts[i],
            ))
        return sections

    def flush_snapshot_now(self) -> bool:
        """One flusher step: export under the guard, write outside it.
        Returns True when a snapshot landed. A deposed leader never writes
        (it would clobber the new leader's snapshot stream)."""
        if not self.is_leader():
            return False
        chunks = self.export_snapshot()
        if chunks is None:
            # Staleness override (doc/fault-model.md "Durable-state plane
            # v2"): the export gate refuses while preempt churn is live,
            # which under sustained churn would starve snapshots forever.
            # Past the staleness budget, arm the wanted flag — the next
            # mutation-bracket exit wakes the flusher for an immediate
            # retry at that quiet point instead of the next interval beat.
            max_stale = self.config.snapshot_max_staleness_seconds
            anchor = self._snapshot_age_anchor
            if (
                max_stale > 0
                and self._ready.is_set()
                and anchor is not None
                and time.monotonic() - anchor > max_stale
            ):
                self._snapshot_flush_wanted = True
            return False
        # _snapshot_write_lock serializes concurrent flushes so chunk
        # families cannot interleave; never held while holding chain locks.
        with self._snapshot_write_lock:
            try:
                self.kube_client.persist_snapshot(chunks)
            except Exception as e:  # noqa: BLE001
                self.metrics.observe_snapshot_persist(False)
                common.log.warning(
                    "snapshot write failed (recovery falls back "
                    "to the previous snapshot or full replay): %s", e,
                )
                return False
        self.metrics.observe_snapshot_persist(True)
        now = time.monotonic()
        self._last_flush_monotonic = now
        self._snapshot_age_anchor = now
        self._snapshot_flush_wanted = False
        return True

    def start_snapshot_flusher(
        self, interval_s: Optional[float] = None
    ) -> bool:
        """Arm the background snapshot flusher: every ``interval_s``
        (default: config snapshotIntervalSeconds; <= 0 disables) it
        serializes + persists a snapshot and settles any wall-clock-expired
        damper holds (the quiet-cluster settling path — no informer events
        needed). Threads are started explicitly, never from __init__, so
        tests and simulators construct schedulers without spawning."""
        interval = (
            self.config.snapshot_interval_seconds
            if interval_s is None
            else interval_s
        )
        if interval <= 0 or self._flusher_thread is not None:
            return False
        stop = threading.Event()
        wake = threading.Event()

        def loop() -> None:
            # wake is the staleness-override doorbell: _exit_mutation
            # sets it at a quiet point when a refused flush left the
            # snapshot past its budget, turning the interval sleep into
            # an immediate retry. The scrubber (scheduler.scrub) also
            # rides these beats — event-clocked, never its own thread.
            while not stop.is_set():
                wake.wait(interval)
                wake.clear()
                if stop.is_set():
                    break
                try:
                    self.settle_health_wall()
                    self.flush_snapshot_now()
                    scrub = self.scrubber
                    if scrub is not None:
                        scrub.tick()
                except Exception:  # noqa: BLE001
                    common.log.exception("snapshot flusher step failed")

        t = threading.Thread(
            target=loop, name="hived-snapshot-flusher", daemon=True
        )
        self._flusher_stop = stop
        self._flusher_wake = wake
        self._flusher_thread = t
        t.start()
        return True

    def stop_snapshot_flusher(self) -> None:
        if self._flusher_stop is not None:
            self._flusher_stop.set()
        if self._flusher_wake is not None:
            self._flusher_wake.set()
        if self._flusher_thread is not None:
            self._flusher_thread.join(timeout=2.0)
        self._flusher_stop = None
        self._flusher_wake = None
        self._flusher_thread = None

    def prefetch_snapshot(self, min_watermark=None, apply: bool = False) -> bool:
        """Standby warm-up (StandbyLoop.on_standby_beat): load + decode +
        validate the latest persisted snapshot and keep the DECODED body
        keyed by its chunk family, so a takeover's recovery skips the
        multi-megabyte JSON decode — the decode happens while standing by,
        off the failover blackout path. Returns True when a validated
        snapshot is warm. The import never mutates the body, so the cached
        object can be handed to recovery as-is.

        ``apply=True`` makes this a HOT standby beat: the projection is
        additionally restored into this process's own core (wholesale,
        repeatable — the restore is state-independent), so the takeover's
        recovery skips even the restore and runs only the delta replay
        against the live cluster. Refused once this scheduler is ready (a
        serving leader must never wholesale-restore under traffic). The
        pre-apply runs outside the mutation bracket on purpose: a standby
        is not the leader and must queue/write nothing — and the restore
        path has no side effects to queue."""
        try:
            chunks = self.kube_client.load_snapshot()
        except Exception as e:  # noqa: BLE001
            common.log.debug("standby snapshot prefetch read failed: %s", e)
            return self._prefetched_snapshot is not None
        if not chunks:
            return False
        cached = self._prefetched_snapshot
        if cached is not None and cached[0] == chunks:
            if not apply or self._preapplied_chunks == chunks:
                return True
            snap = cached[1]
        else:
            snap, reason = snapshot_mod.decode(
                chunks, self._config_fingerprint, min_watermark
            )
            if snap is None:
                common.log.debug(
                    "standby snapshot prefetch unusable: %s", reason
                )
                return False
            self._prefetched_snapshot = (chunks, snap)
        corrupt = snap.get("_corrupt") or {}
        partial = bool(corrupt.get("sections") or corrupt.get("chains"))
        if apply and not self._ready.is_set():
            if partial:
                # PARTIAL pre-apply: restore the healthy chain-family
                # sections scoped on a fresh core NOW (the expensive
                # restore runs on an idle standby beat, off the failover
                # blackout path) and remember the demoted chain set. The
                # gate here runs against whatever ledger the standby has
                # (usually none); takeover re-gates against the real
                # crash ledger and only trusts this pre-apply when both
                # demote exactly the same chains — else it discards and
                # restores scoped in-window, the plain partial path. The
                # gate mutates ok flags, so it runs on copies to keep the
                # cached decode pristine for the takeover's own gate.
                families = snap.get("_families")
                if not families:
                    return True  # monolithic corruption never decodes
                fams = [dict(f) for f in families]
                usable, replay_chains, _n = (
                    self._gate_sectioned_snapshot(fams)
                )
                if not usable:
                    return True  # keep the decode warm, nothing to apply
                scope = frozenset(str(c) for c in replay_chains)
                if (
                    self._preapplied_chunks == chunks
                    and self._preapplied_replay == scope
                ):
                    return True  # idle beat, unchanged family: no-op
                try:
                    self._clear_imported_state()
                    self._swap_fresh_core()
                    self._import_snapshot_partial(
                        snap, fams, replay_chains, live_names=None
                    )
                    self._preapplied_chunks = list(chunks)
                    self._preapplied_replay = scope
                except Exception:  # noqa: BLE001
                    common.log.exception(
                        "hot-standby partial pre-apply failed; takeover "
                        "will restore from the decoded snapshot instead",
                    )
                    self._clear_imported_state()
                return True
            try:
                self._clear_imported_state()
                self._import_snapshot_state(snap, live_names=None)
                self._preapplied_chunks = list(chunks)
            except Exception:  # noqa: BLE001
                common.log.exception(
                    "hot-standby pre-apply failed; takeover will restore "
                    "from the decoded snapshot instead",
                )
                self._clear_imported_state()
                self._preapplied_chunks = None
        return True

    def discard_preapplied_state(self) -> None:
        """Hot-standby state with no usable snapshot at takeover (it was
        corrupted or deleted after the pre-apply): discard the pre-applied
        projection wholesale — the full replay must start from a virgin
        core, and the _snapshot_pending fingerprint fast path must not
        confirm any of the discarded imports in O(1). No-op unless a
        pre-apply is live. Called by BOTH recovery drivers (recover() and
        the InformerLoop boot path) when load_valid_snapshot comes back
        empty."""
        if self._preapplied_chunks is None:
            return
        self._clear_imported_state()
        old_core = self.core
        core = HivedCore(self.config)
        core.decisions = self.decisions
        core.lock_validator = self._locks.require_global
        core.preemption_observer = self._on_preemption_event
        core.preempt_rng = old_core.preempt_rng
        self.core = core
        if self.recorder is not None:
            self.recorder.force_reanchor()

    def _clear_imported_state(self) -> None:
        """Drop everything a snapshot import populated at the framework
        level (the core side needs no clearing — restore_projection is
        state-independent). Used between repeated hot-standby pre-applies
        and before re-importing a changed snapshot at takeover."""
        with self._lock:
            self.pod_schedule_statuses.clear()
            self.quarantined_pods.clear()
            self._snapshot_pending.clear()
            self._snapshot_claims.clear()
            self._snapshot_released_uids.clear()
            self._chip_targets.clear()
            self._damper.reset()
            self._preapplied_chunks = None
            self._preapplied_replay = None
        self._wait_cache_clear()

    def load_valid_snapshot(self, min_watermark=None) -> Optional[Dict]:
        """Load + validate the persisted snapshot. None (with
        snapshotFallbackCount bumped when one EXISTED but was unusable)
        means: run the full annotation replay. A missing snapshot is not a
        fallback — it is simply a first boot.

        A warm standby that prefetched the identical chunk family serves
        the already-decoded body (byte-equality of the chunks is the cache
        key, so a snapshot rewritten between prefetch and takeover decodes
        fresh); the watermark floor is still re-checked — the validation
        ladder is never skipped, only the decode."""
        chunks = None
        self._last_snapshot_chunks = None
        try:
            chunks = self.kube_client.load_snapshot()
        except Exception as e:  # noqa: BLE001
            common.log.warning(
                "snapshot ConfigMap read failed; recovering by full "
                "annotation replay: %s", e,
            )
            self.metrics.observe_snapshot_fallback()
            return None
        if not chunks:
            return None
        self._last_snapshot_chunks = chunks
        cached = self._prefetched_snapshot
        if cached is not None and cached[0] == chunks:
            snap, reason = cached[1], ""
            if min_watermark is not None and snapshot_mod._watermark_older(
                snap.get("_meta", {}).get("watermark"), min_watermark
            ):
                snap, reason = None, "stale watermark (prefetched)"
        else:
            snap, reason = snapshot_mod.decode(
                chunks, self._config_fingerprint, min_watermark
            )
        if snap is None:
            common.log.warning(
                "persisted snapshot unusable (%s); recovering by full "
                "annotation replay", reason,
            )
            self.metrics.observe_snapshot_fallback()
        return snap

    def import_snapshot(self, snap: Dict, nodes: List[Node]) -> bool:
        """Reinstate a validated snapshot's projection wholesale. On ANY
        failure mid-import the partially-mutated state is discarded
        (_reset_for_full_replay) and recovery proceeds as a full annotation
        replay — degraded recovery must be deterministic, never a function
        of how far the import got.

        Doomed-ledger gate: the advisory doomed bindings are
        history-dependent (that is why the ledger ConfigMap exists), and
        organic doom churn is SUSPENDED during recovery — there is no
        incremental mechanism to converge a snapshot's doomed set onto the
        fresher ledger's. A snapshot whose dooms do not exactly match the
        crash ledger is therefore stale for the doom subsystem and falls
        back to the full replay (which binds the ledger's dooms on the
        bootstrap state, the proven PR-3 path). The window is one doom
        change between the last flush and the crash — rare at production
        cadence, and the fallback is the deterministic degraded mode the
        fault model already guarantees.

        At schema v3 both the gate and the fallback are SECTION-GRANULAR
        (doc/fault-model.md "Durable-state plane v2"): each chain-family
        section is doom-gated against the ledger's entries for its own
        chains, and a corrupt or diverged family demotes to the scoped
        annotation replay (mode "snapshot+partial") while every healthy
        section restores wholesale. Monolithic layouts (v2 read-compat,
        single-body v3) keep the historical all-or-nothing behavior."""
        chunks = self._last_snapshot_chunks
        preapplied = (
            self._preapplied_chunks is not None
            and chunks == self._preapplied_chunks
        )
        families = snap.get("_families") or snapshot_mod._single_family(snap)
        sectioned = any(f.get("chains") is not None for f in families)
        if sectioned:
            usable, replay_chains, n_fallback = (
                self._gate_sectioned_snapshot(families)
            )
        else:
            usable = self._snapshot_dooms_match_ledger(snap)
            replay_chains, n_fallback = set(), 0
        if not usable:
            common.log.warning(
                "persisted snapshot's doomed bindings diverge from the "
                "crash ledger (or no chain-family section survived); "
                "recovering by full annotation replay",
            )
            self.metrics.observe_snapshot_fallback()
            if preapplied or self._preapplied_chunks is not None:
                self._reset_for_full_replay(nodes)
            else:
                # begin_recovery deferred the doom rebuild to this import;
                # the full replay it falls back to still needs it.
                self.core.rebuild_doomed_from_ledger()
            return False
        live_names = {n.name for n in nodes}
        if replay_chains:
            if (
                preapplied
                and self._preapplied_replay is not None
                and self._preapplied_replay
                == {str(c) for c in replay_chains}
            ):
                # Hot-standby PARTIAL fast path: the healthy families are
                # already restored in this process (pre-applied on a
                # standby beat with the SAME replay scope this gate just
                # computed), so the blackout shrinks to the demoted
                # chains' annotation replay plus the node delta. The
                # scoped doom rebuild re-runs here because the standby
                # gated against its own (possibly absent) ledger copy
                # while begin_recovery just installed the real one.
                with self._lock:
                    for name in self.core.configured_node_names():
                        if name not in live_names:
                            self.core.set_bad_node(name)
                    for n, chips in self.core.bad_chips.items():
                        self._chip_targets[n] = set(chips)
                    self.core.rebuild_doomed_from_ledger(
                        chains={str(c) for c in replay_chains}
                    )
                self.metrics.observe_snapshot_section_fallback(n_fallback)
                common.log.warning(
                    "partial snapshot fallback (hot standby): %d "
                    "section(s) covering chain(s) %s replay from "
                    "annotations; every other section was pre-applied",
                    n_fallback, sorted(replay_chains),
                )
                self._recovery_mode = "snapshot+partial"
                return True
            # PARTIAL fallback: the demoted families' chains replay from
            # annotations (the existing delta path) while the rest of the
            # snapshot restores wholesale — the plane degrades in
            # proportion to the damage, not in one cliff.
            try:
                if self._preapplied_chunks is not None:
                    # A scoped restore is only meaningful on a virgin
                    # core; discard any pre-applied projection wholesale.
                    self._clear_imported_state()
                    self._swap_fresh_core()
                self._import_snapshot_partial(
                    snap, families, replay_chains, live_names
                )
            except Exception:  # noqa: BLE001
                common.log.exception(
                    "partial snapshot import failed mid-way; resetting "
                    "for full annotation replay",
                )
                self.metrics.observe_snapshot_fallback()
                self._reset_for_full_replay(nodes)
                return False
            self.metrics.observe_snapshot_section_fallback(n_fallback)
            common.log.warning(
                "partial snapshot fallback: %d section(s) covering "
                "chain(s) %s replay from annotations; every other "
                "section restored", n_fallback, sorted(replay_chains),
            )
            self._recovery_mode = "snapshot+partial"
            return True
        try:
            if preapplied:
                # Hot standby: the projection is already live in this
                # process (pre-applied on a standby beat); only normalize
                # nodes the live cluster no longer has. This is the
                # takeover fast path — the blackout is just the delta
                # replay.
                with self._lock:
                    for name in self.core.configured_node_names():
                        if name not in live_names:
                            self.core.set_bad_node(name)
                    for n, chips in self.core.bad_chips.items():
                        self._chip_targets[n] = set(chips)
            else:
                if self._preapplied_chunks is not None:
                    # Pre-applied state from an OLDER snapshot: discard it
                    # wholesale and restore the current one.
                    self._clear_imported_state()
                self._import_snapshot_state(snap, live_names)
        except Exception:  # noqa: BLE001
            common.log.exception(
                "snapshot import failed mid-way; resetting for full "
                "annotation replay",
            )
            self.metrics.observe_snapshot_fallback()
            self._reset_for_full_replay(nodes)
            return False
        self._recovery_mode = "snapshot+delta"
        return True

    def _ledger_dooms(self) -> Set[Tuple[str, str, int, str]]:
        ledger = self._recovery_ledger
        if not isinstance(ledger, dict):
            # No authoritative ledger (first boot or failed read): organic
            # dooming is live during recovery, which a verbatim restore
            # cannot reproduce — unless neither side has any doom at all.
            ledger = {}
        return {
            (str(vcn), str(e.get("chain")), int(e.get("level", -1)),
             str(e.get("address")))
            for vcn, entries in (ledger.get("vcs") or {}).items()
            for e in entries
        }

    @staticmethod
    def _core_dooms(core_body: Dict) -> Set[Tuple[str, str, int, str]]:
        return {
            (str(vcn), str(chain), int(level), str(addr))
            for vcn, per_chain in (
                core_body.get("vcDoomed") or {}
            ).items()
            for chain, levels in per_chain.items()
            for level, addrs in levels.items()
            for addr in addrs
        }

    def _snapshot_dooms_match_ledger(self, snap: Dict) -> bool:
        return self._core_dooms(snap.get("core") or {}) == (
            self._ledger_dooms()
        )

    def _chain_node_map(self) -> Dict[str, Set[str]]:
        """chain name -> the node-name set of its chain FAMILY (config
        static; family_node_names caches the underlying walk)."""
        out: Dict[str, Set[str]] = {}
        for chains, node_set in zip(
            self.core.compiled.families, self.core.family_node_names()
        ):
            for c in chains:
                out[str(c)] = node_set
        return out

    def _gate_sectioned_snapshot(
        self, families: List[Dict]
    ) -> Tuple[bool, Set[str], int]:
        """Per-family doom gate + spanning-node demotion closure for a
        SECTIONED snapshot (schema v3). Mutates the ``ok`` flags in
        place; returns ``(usable, replay_chains, n_fallback)`` where
        replay_chains is every configured chain that must replay from
        annotations (corrupt sections, doom-diverged families, and any
        chain no healthy section covers) and n_fallback the count of
        family sections that fell back. usable=False means no healthy
        family survived — the snapshot is as good as absent.

        The doom gate is the PR-7 ledger gate, SCOPED: a family whose
        restored dooms diverge from the crash ledger's entries for its
        own chains is stale for the doom subsystem and demotes to the
        annotation replay (which rebinds the ledger's dooms on bootstrap
        state), without dragging healthy families down with it.

        The closure exists because node-level health is not splittable:
        a host carrying BOTH a replaying and a restoring family would
        need its health record half-applied. Families are leaf-SKU
        connected components, so the closure only fires on heterogeneous
        hosts — and it runs to a fixpoint because each round only ever
        demotes."""
        ledger_dooms = self._ledger_dooms()
        for fam in families:
            if not fam["ok"]:
                continue
            fam_chains = {str(c) for c in fam["chains"] or ()}
            want = {d for d in ledger_dooms if d[1] in fam_chains}
            if self._core_dooms(fam.get("core") or {}) != want:
                fam["ok"] = False
                common.log.warning(
                    "snapshot section %r: doomed bindings diverge from "
                    "the crash ledger; demoting its chains to annotation "
                    "replay", fam.get("name"),
                )
        chain_nodes = self._chain_node_map()
        all_chains = {str(c) for c in self.core.full_cell_list}

        def nodes_of(chains) -> Set[str]:
            out: Set[str] = set()
            for c in chains:
                out |= chain_nodes.get(str(c), set())
            return out

        while True:
            replay_chains = all_chains - {
                str(c)
                for f in families
                if f["ok"]
                for c in f["chains"] or ()
            }
            replay_nodes = nodes_of(replay_chains)
            spanned = [
                f for f in families
                if f["ok"] and nodes_of(f["chains"] or ()) & replay_nodes
            ]
            if not spanned:
                break
            for f in spanned:
                f["ok"] = False
                common.log.warning(
                    "snapshot section %r: shares host(s) with a replaying "
                    "family; demoting to annotation replay too",
                    f.get("name"),
                )
        n_fallback = sum(1 for f in families if not f["ok"])
        if not any(f["ok"] for f in families):
            return False, all_chains, n_fallback
        return True, replay_chains, n_fallback

    def _swap_fresh_core(self) -> None:
        """A SCOPED restore (partial fallback) is only meaningful on a
        VIRGIN core: out-of-scope chains must sit in the constructor
        bootstrap state (all nodes bad, bad-free lists full) — exactly
        where the full annotation replay starts — not in whatever a
        hot-standby pre-apply left behind. Discards the core wholesale
        and re-installs the decoded ledger preferences."""
        old = self.core
        core = HivedCore(self.config)
        core.decisions = self.decisions
        core.lock_validator = self._locks.require_global
        core.preemption_observer = self._on_preemption_event
        core.preempt_rng = old.preempt_rng
        self.core = core
        self._wait_cache_clear()
        core.set_preferred_doomed(self._recovery_ledger)
        if self.recorder is not None:
            self.recorder.force_reanchor()

    def _import_snapshot_partial(
        self,
        snap: Dict,
        families: List[Dict],
        replay_chains: Set[str],
        live_names: Optional[Set[str]],
    ) -> None:
        """Restore the healthy chain-family sections wholesale and leave
        the replay chains in bootstrap state for the annotation replay —
        the projection-side half of the partial fallback. Health is
        COMPOSED: the snapshot's record minus the replaying hosts (their
        chip badness re-derives from live node annotations exactly as a
        full replay would), with every replaying host forced bad so the
        node replay's heal transition fires on it (set_bad_node no-ops on
        the bootstrap state, so the forcing is idempotent)."""
        ok_fams = [f for f in families if f["ok"]]
        healthy_chains = {
            str(c) for f in ok_fams for c in f["chains"] or ()
        }
        chainless = snap.get("_chainless") or {"groups": {}, "pods": []}
        core_body = snapshot_mod.merge_core_slices(
            [f["core"] for f in ok_fams]
        )
        core_body.setdefault("groups", {}).update(
            chainless.get("groups") or {}
        )
        pod_recs: List[Dict] = []
        for f in ok_fams:
            pod_recs.extend(f["pods"])
        pod_recs.extend(chainless.get("pods") or [])
        chain_nodes = self._chain_node_map()
        replay_nodes: Set[str] = set()
        for c in replay_chains:
            replay_nodes |= chain_nodes.get(str(c), set())
        health = dict(snap.get("health") or {})
        health["badNodes"] = sorted(
            set(health.get("badNodes") or ()) | replay_nodes
        )
        health["badChips"] = {
            n: v
            for n, v in (health.get("badChips") or {}).items()
            if n not in replay_nodes
        }
        health["drainingChips"] = {
            n: v
            for n, v in (health.get("drainingChips") or {}).items()
            if n not in replay_nodes
        }
        with self._lock:
            self.core.restore_projection(
                core_body, health, live_names, chains=healthy_chains
            )
            self._damper.reset()
            for n, chips in self.core.bad_chips.items():
                self._chip_targets[n] = set(chips)
            imported = self._attach_snapshot_pods_locked(pod_recs)
            # The replay chains' advisory dooms rebuild from the crash
            # ledger on their bootstrap cells — the proven PR-3 full
            # replay path, scoped; the restored chains carry the ledger's
            # dooms verbatim (the gate enforced exact equality).
            self.core.rebuild_doomed_from_ledger(
                chains={str(c) for c in replay_chains}
            )
        self._snapshot_imported_count = imported
        self._snapshot_delta_count = 0
        if self.recorder is not None:
            self.recorder.force_reanchor()

    def _import_snapshot_state(
        self, snap: Dict, live_names: Optional[Set[str]]
    ) -> None:
        """Restore the projection + framework maps. ``live_names`` is the
        live node list for absent-node normalization; None during a
        hot-standby pre-apply (the takeover normalizes against the real
        list)."""
        with self._lock:
            # The restored doomed bindings ARE the ledger's (the gate in
            # import_snapshot verified exact equality), carried with the
            # continuous scheduler's own virtual-cell choices — no rebuild
            # pass needed or wanted (retire+rebind churn could only pick
            # differently).
            self.core.restore_projection(
                snap["core"], snap.get("health"), live_names
            )
            # The damper's applied-state memory described the pre-restore
            # core; against the restored records it would swallow the node
            # replay's re-observations as non-flips.
            self._damper.reset()
            # Seed the chip observation targets from the restored records:
            # a chip bad in the snapshot but healed while we were down must
            # be RE-OBSERVED healthy by the node replay, which only walks
            # the live device-health annotation plus these targets.
            for n, chips in self.core.bad_chips.items():
                self._chip_targets[n] = set(chips)
            imported = self._attach_snapshot_pods_locked(
                snap.get("pods") or []
            )
        self._snapshot_imported_count = imported
        self._snapshot_delta_count = 0
        if self.recorder is not None:
            # restore_projection writes cell fields directly: the current
            # recording window's anchor no longer describes this state.
            self.recorder.force_reanchor()

    def _attach_snapshot_pods_locked(self, pod_recs: List[Dict]) -> int:
        """Decode-free pod slotting shared by the wholesale and partial
        imports (caller holds the guard): the cell state is already
        restored verbatim, so each record only names its group slot. The
        delta replay re-checks every pod against its live annotations
        before trusting the import. Returns the count imported."""
        imported = 0
        for rec in pod_recs:
            pod = Pod(
                name=rec["name"],
                namespace=rec["namespace"],
                uid=rec["uid"],
                annotations=dict(rec["annotations"]),
                node_name=rec["node"],
                phase=rec.get("phase", "Running"),
                resource_limits={
                    str(k): int(v)
                    for k, v in (rec.get("resourceLimits") or {}).items()
                },
            )
            self.core.attach_restored_pod(
                rec["spec"]["affinityGroup"]["name"],
                int(rec["spec"]["leafCellNumber"]),
                int(rec["podIndex"]),
                pod,
            )
            self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                pod=pod, pod_state=PodState.BOUND
            )
            self._snapshot_pending[pod.uid] = (
                self._snapshot_pod_fingerprint(pod)
            )
            info = rec["bindInfo"]
            for idx in info["leafCellIsolation"]:
                self._snapshot_claims[
                    (info["cellChain"], info["node"], idx)
                ] = pod.uid
            imported += 1
        return imported

    @staticmethod
    def _snapshot_pod_fingerprint(pod: Pod) -> Tuple:
        """What makes an imported pod's state trustworthy against its live
        twin: same node and same spec/bind-info annotations. Anything else
        (phase flips, unrelated annotations) does not affect placement."""
        return (
            pod.node_name,
            pod.annotations.get(constants.ANNOTATION_POD_SCHEDULING_SPEC),
            pod.annotations.get(constants.ANNOTATION_POD_BIND_INFO),
        )

    def _reset_for_full_replay(self, nodes: List[Node]) -> None:
        """Discard everything a partial snapshot import mutated: fresh
        core, cleared trackers, the decoded ledger re-installed, and the
        node replay re-run. Runs inside the recovery mutation bracket
        before any live-pod replay, so the subsequent full replay is
        byte-identical to a recovery that never saw a snapshot."""
        old = self.core
        core = HivedCore(self.config)
        core.decisions = self.decisions
        core.lock_validator = self._locks.require_global
        core.preemption_observer = self._on_preemption_event
        core.preempt_rng = old.preempt_rng
        self.core = core
        # The fresh core's epochs restart at 0: a certificate issued
        # against the old core could compare equal by coincidence.
        self._wait_cache_clear()
        self.pod_schedule_statuses.clear()
        self.quarantined_pods.clear()
        self._snapshot_pending.clear()
        self._snapshot_claims.clear()
        self._snapshot_released_uids.clear()
        self._snapshot_imported_count = 0
        self._snapshot_delta_count = 0
        self._damper = health_mod.FlapDamper(
            self.config.health_flap_threshold,
            self.config.health_flap_window,
            self.config.health_flap_hold,
            hold_seconds=self.config.health_flap_hold_seconds,
        )
        self._chip_targets.clear()
        self._stranded_names = set()
        self.nodes.clear()
        core.set_preferred_doomed(self._recovery_ledger)
        core.rebuild_doomed_from_ledger()
        self._recovery_mode = "full"
        for node in nodes:
            self.add_node(node)

    def _snapshot_claims_conflict(self, pod: Pod) -> bool:
        """True when ``pod``'s bind-info leaf cells overlap cells a
        still-unconfirmed snapshot import holds — the one way the import
        can contradict the live cluster (the holder was deleted while we
        were down and its cells were reused)."""
        try:
            info = extract_pod_bind_info(pod)
        except api.WebServerError:
            return False  # undecodable: the replay below quarantines it
        for idx in info.leaf_cell_isolation:
            uid = self._snapshot_claims.get((info.cell_chain, info.node, idx))
            if (
                uid is not None
                and uid != pod.uid
                and uid in self._snapshot_pending
            ):
                return True
        return False

    def _release_pending_snapshot_imports_locked(self) -> None:
        """Release every imported-but-unconfirmed snapshot pod (caller
        already holds the global guard): the conflict-repair half of the
        delta replay — invoked when a live pod's replay collides with
        imported state the live cluster has since superseded."""
        for uid in sorted(self._snapshot_pending):
            status = self.pod_schedule_statuses.get(uid)
            if status is not None:
                self._delete_pod_locked(status.pod)
            self._snapshot_released_uids.add(uid)
            self._snapshot_delta_count += 1
        self._snapshot_pending.clear()
        self._snapshot_claims.clear()

    def _readd_released_snapshot_pods(self, pods: List[Pod]) -> None:
        """Re-admit live pods whose snapshot import was released by a claim
        conflict after their position in the replay had already passed —
        they replay from their live annotations, exactly as full replay
        admitted them."""
        if not self._snapshot_released_uids:
            return
        released = self._snapshot_released_uids
        self._snapshot_released_uids = set()
        for pod in pods:
            if (
                pod.uid in released
                and is_interested(pod)
                and pod.uid not in self.pod_schedule_statuses
                and pod.uid not in self.quarantined_pods
            ):
                try:
                    self.add_pod(pod)
                except Exception as e:  # noqa: BLE001
                    self._quarantine_pod(pod, e)

    def _drop_vanished_snapshot_pods(self) -> None:
        """The deletion half of the delta replay: imported pods the live
        list never confirmed were deleted while we were down — release
        their cells exactly as the informer's DELETED event would have."""
        if not self._snapshot_pending:
            return
        for uid in sorted(self._snapshot_pending):
            status = self.pod_schedule_statuses.get(uid)
            if status is not None:
                with self._lock:
                    common.log.warning(
                        "[%s]: imported from snapshot but absent from the "
                        "live cluster (deleted while down); releasing",
                        status.pod.key,
                    )
                    self._delete_pod_locked(status.pod)
            self._snapshot_delta_count += 1
        self._snapshot_pending.clear()
        self._snapshot_claims.clear()

    def _quarantine_pod(self, pod: Pod, error: Exception) -> None:
        """Park an unreplayable bound pod: logged, counted, surfaced via the
        inspect API, and excluded from the scheduling view. Callable from
        any section — the record map is guarded by the innermost
        side-effect lock (a chain section must not widen to the global
        guard)."""
        with self._side_effect_lock:
            if pod.uid in self.quarantined_pods:
                return
            common.log.error(
                "[%s]: quarantining pod bound to node %s: recovery replay "
                "failed: %s", pod.key, pod.node_name, error,
            )
            self.quarantined_pods[pod.uid] = QuarantineRecord(
                pod=pod,
                reason=f"{type(error).__name__}: {error}",
                quarantined_at=time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                ),
            )
            self.metrics.observe_quarantine()

    def get_quarantine(self) -> Dict:
        """Inspect payload for /v1/inspect/quarantine."""
        with self._lock:
            return {
                "items": [
                    r.to_dict() for r in self.quarantined_pods.values()
                ]
            }

    # ------------------------------------------------------------------ #
    # Node events (reference: scheduler.go:218-251), routed through the
    # hardware health plane: ready-state and per-chip device health pass
    # the flap damper; drains apply directly.
    # ------------------------------------------------------------------ #

    def add_node(self, node: Node) -> None:
        top = self._blackbox_top()
        self._enter_mutation()
        try:
            t0 = time.monotonic()
            with self._lock:
                self.nodes[node.name] = node
                self._observe_node_health(node)
            self._note_boot_node_add(time.monotonic() - t0)
        finally:
            self._exit_mutation()
            if top:
                self._blackbox_record("record_node_event", "node_add", node)
                self._blackbox_tick()

    def add_nodes(self, nodes: List[Node]) -> None:
        """Batched node adds (informer boot; doc/hot-path.md "Boot and
        transport plane"): one mutation bracket and ONE global-mode lock
        acquisition for the whole initial node list, instead of a
        per-node acquire/release churn — at 10k+ hosts the per-event
        overhead was a visible slice of the nodeAdd boot phase. Semantics
        per node are exactly add_node's."""
        if not nodes:
            return
        top = self._blackbox_top()
        self._enter_mutation()
        try:
            t0 = time.monotonic()
            with self._lock:
                for node in nodes:
                    self.nodes[node.name] = node
                    self._observe_node_health(node)
            self._note_boot_node_add(time.monotonic() - t0)
        finally:
            self._exit_mutation()
            if top:
                for node in nodes:
                    self._blackbox_record(
                        "record_node_event", "node_add", node
                    )
                self._blackbox_tick()

    def _note_boot_node_add(self, seconds: float) -> None:
        """Accumulate node-add wall time into the boot-phase ledger until
        the scheduler turns ready (after that, node events are steady-
        state traffic, not boot)."""
        if not self._ready.is_set():
            phases = self.core.boot_phase_seconds
            phases["nodeAdd"] = phases.get("nodeAdd", 0.0) + seconds

    def update_node(self, old: Node, new: Node) -> None:
        if self._node_event_is_noop(new):
            # Relist fast path (doc/hot-path.md "Warehouse-scale profile"):
            # every informer gap repair re-delivers the WHOLE node list, and
            # at fleet scale almost none of it changed — each no-change
            # update used to acquire the global (all-chains) lock order just
            # to feed the damper an observation it would discard. When the
            # node's health-relevant projection (ready-state, device-health
            # chips, drain annotation) matches what is already applied and
            # the damper holds nothing, skip the lock entirely. Replacing a
            # present key is atomic under the GIL (no dict resize), so
            # concurrent readers holding the lock never see a torn map.
            self.nodes[new.name] = new
            self.metrics.observe_node_event_noop()
            return
        top = self._blackbox_top()
        # Captured BEFORE the verb (the projection cache moves inside);
        # the event itself records after, like every black-box hook.
        prev_proj = (
            self._node_projections.get(new.name)
            if top and self.recorder is not None
            else None
        )
        self._enter_mutation()
        try:
            with self._lock:
                self.nodes[new.name] = new
                self._observe_node_health(new)
        finally:
            self._exit_mutation()
            if top:
                self._blackbox_record(
                    "record_node_event", "node_state", new,
                    self._fault_kind_from_projections(
                        prev_proj, self._node_health_projection(new)
                    ),
                )
                self._blackbox_tick()

    def delete_node(self, node: Node) -> None:
        top = self._blackbox_top()
        self._enter_mutation()
        try:
            with self._lock:
                self.nodes.pop(node.name, None)
                # The node's flap history and chip targets die with it; the
                # core lifts its drain and marks it bad.
                self._damper.forget_node(node.name)
                self._chip_targets.pop(node.name, None)
                self._node_projections.pop(node.name, None)
                self.core.delete_node(node)
                self.metrics.observe_health_transition()
                self._check_stranded_locked()
        finally:
            self._exit_mutation()
            if top:
                self._blackbox_record(
                    "record_node_event", "node_delete", node
                )
                self._blackbox_tick()

    # ------------------------------------------------------------------ #
    # Health plane (doc/fault-model.md "Hardware health plane")
    # ------------------------------------------------------------------ #

    @staticmethod
    def _node_health_projection(node: Node) -> Tuple:
        """Everything _observe_node_health reads off a node object: the
        ready/schedulable verdict, the bad-chip set (annotation + per-chip
        conditions), and the raw drain annotation. Two nodes with equal
        projections are indistinguishable to the health plane."""
        return (
            is_node_healthy(node),
            frozenset(health_mod.device_bad_chips(node)),
            node.annotations.get(
                constants.ANNOTATION_NODE_DRAIN, ""
            ).strip(),
        )

    def _node_event_is_noop(self, new: Node) -> bool:
        """True when an update event for a known node carries no
        health-relevant change AND nothing is pending that the slow path
        would progress (damper holds, eviction retries, recovery). Reads
        are lock-free: the cached projection and the damper count are
        GIL-atomic, and a racing real transition re-delivers through its
        own (locked) event, so a stale skip here can never lose state the
        cluster still wants — the projection is compared against what was
        last APPLIED, not against the caller's old object."""
        if (
            not self.node_event_fastpath
            or self._in_recovery
            or self._eviction_retry_pending
            or self._damper.pending_count() > 0
        ):
            return False
        applied = self._node_projections.get(new.name)
        if applied is None:
            return False
        return applied == self._node_health_projection(new)

    def _observe_node_health(self, node: Node) -> None:
        """Under the lock: feed the node's desired health (ready-state +
        device-health chips) through the flap damper, apply what the damper
        admits plus anything it settles, and reconcile the (undamped) drain
        annotation.

        The damper clock deliberately does NOT advance per observation:
        it ticks only via health_tick() (informer relists and watch-cycle
        ends, or one tick per harness event). Advancing per node event
        would make the window cluster-size-dependent — with more
        heartbeating nodes than `health_flap_window`, one node's
        consecutive flips would always fall out of its own window and
        damping would be mathematically inert at fleet scale."""
        clock = self._health_clock
        applied = False
        applied |= self._observe_target(
            ("node", node.name), is_node_healthy(node), clock
        )
        bad_chips = health_mod.device_bad_chips(node)
        targets = self._chip_targets.setdefault(node.name, set())
        targets |= bad_chips
        for chip in sorted(targets):
            applied |= self._observe_target(
                ("chip", node.name, chip), chip not in bad_chips, clock
            )
        applied |= self._apply_settled(clock)
        drain = health_mod.drain_chip_indices(
            node, self.core.node_chip_indices(node.name)
        )
        if drain != self.core.draining_chips.get(node.name, set()):
            self.core.apply_drain(node.name, drain)
            applied = True
        # The no-op fast path's baseline: the projection this (locked)
        # observation just processed. A held transition keeps pending>0,
        # which disables skipping until it settles.
        self._node_projections[node.name] = (
            self._node_health_projection(node)
        )
        if applied and not self._in_recovery:
            # Not during recovery: the replay applies one transition per
            # node and a per-transition group scan would make recovery
            # O(nodes x groups) — and the snapshot path restores groups
            # BEFORE the node replay, so an early stranded-eviction there
            # would diverge from full replay (which has no groups yet).
            # finish_recovery seeds the stranded gauge once at the end.
            self._check_stranded_locked()

    def _observe_target(self, target, desired_healthy: bool, clock) -> bool:
        rec_before = self._damper.pending_count()
        if self._damper.observe(target, desired_healthy, clock):
            self._apply_health_transition(target, desired_healthy)
            return True
        if self._damper.pending_count() > rec_before:
            self.metrics.observe_health_damped()
        return False

    def _apply_health_transition(self, target, healthy: bool) -> None:
        if target[0] == "node":
            if healthy:
                self.core.set_healthy_node(target[1])
            else:
                self.core.set_bad_node(target[1])
        else:  # ("chip", node, index)
            if healthy:
                self.core.set_healthy_leaf(target[1], target[2])
            else:
                self.core.set_bad_leaf(target[1], target[2])
        self.metrics.observe_health_transition()

    def _apply_settled(self, clock) -> bool:
        applied = False
        for target, healthy in self._damper.settled(clock):
            self._apply_health_transition(target, healthy)
            self.metrics.observe_health_settled()
            applied = True
        return applied

    def health_tick(self) -> None:
        """Advance the event clock without a node observation, settling any
        quiet held transitions. Called by the informer on relists (and by
        harnesses each event) so a flap that simply stops still settles."""
        top = self._blackbox_top()
        self._enter_mutation()
        try:
            with self._lock:
                self._health_clock += 1
                if self._apply_settled(self._health_clock):
                    self._check_stranded_locked()
                if self.defrag is not None:
                    # The defragmenter rides the same event clock as flap
                    # damping: deterministic under the chaos harness, free
                    # on quiet clusters.
                    self.defrag.tick_locked(self._health_clock)
        finally:
            self._exit_mutation()
            if top:
                self._blackbox_record("record_marker", "health_tick")
                self._blackbox_tick()

    def settle_health_wall(self) -> None:
        """Apply damper holds whose WALL-CLOCK floor expired (no event tick
        needed): the background snapshot flusher calls this every interval
        so a quiet cluster — no informer relist/watch-cycle traffic to
        drive health_tick — still settles within healthFlapHoldSeconds.
        No-op when the floor is disabled (the chaos default: the event
        clock stays exclusively authoritative)."""
        if self._damper.hold_seconds <= 0:
            return
        top = self._blackbox_top()
        self._enter_mutation()
        try:
            with self._lock:
                if self._apply_settled(self._health_clock):
                    self._check_stranded_locked()
        finally:
            self._exit_mutation()
            if top:
                # Wall-clock-driven settles are inherently time-coupled;
                # recording the verb at its stream position preserves the
                # ORDER a replay needs (scheduler.recorder).
                self._blackbox_record("record_marker", "settle_health_wall")
                self._blackbox_tick()

    def settle_health_now(self) -> None:
        """Force-apply every held transition immediately (teardown and
        restart-projection paths that need the damper drained)."""
        top = self._blackbox_top()
        self._enter_mutation()
        try:
            with self._lock:
                applied = False
                for target, healthy in self._damper.force_settle():
                    self._apply_health_transition(target, healthy)
                    self.metrics.observe_health_settled()
                    applied = True
                if applied:
                    self._check_stranded_locked()
        finally:
            self._exit_mutation()
            if top:
                self._blackbox_record("record_marker", "settle_health")
                self._blackbox_tick()

    def health_pending_count(self) -> int:
        with self._lock:
            return self._damper.pending_count()

    # -------------- defragmenter verbs (scheduler.defrag) -------------- #

    def run_defrag_cycle_now(self) -> int:
        """Force one defragmentation cycle immediately (chaos/sim drivers
        and the `/v1/inspect/health` walkthrough; production runs off the
        health event clock). Returns the number of NEW proposals."""
        if self.defrag is None:
            return 0
        top = self._blackbox_top()
        self._enter_mutation()
        try:
            with self._lock:
                return self.defrag.run_cycle_locked()
        finally:
            self._exit_mutation()
            if top:
                self._blackbox_record("record_marker", "defrag_cycle")
                self._blackbox_tick()

    def take_defrag_proposals(self) -> List[Dict]:
        """Drain the defragmenter's pending migration proposals (the
        workload-controller side of the drain handshake: the sim tier and
        chaos harness checkpoint + delete + resubmit the named gangs)."""
        if self.defrag is None:
            return []
        proposals = self.defrag.take_proposals()
        if self._blackbox_top():
            self._blackbox_record("record_marker", "defrag_take")
        return proposals

    def _stranded_groups_locked(self) -> List[Dict]:
        """Gangs holding bad or draining cells — placed before the hardware
        degraded (new placements never land on such cells)."""
        out: List[Dict] = []
        for name, g in sorted(self.core.affinity_groups.items()):
            bad: List[str] = []
            draining: List[str] = []
            for rows in g.physical_placement.values():
                for row in rows:
                    for leaf in row:
                        if leaf is None:
                            continue
                        if not leaf.healthy:
                            bad.append(leaf.address)
                        elif leaf.draining:
                            draining.append(leaf.address)
            if bad or draining:
                out.append(
                    {
                        "name": name,
                        "vc": str(g.vc),
                        "state": g.state.value,
                        "badCells": sorted(bad),
                        "drainingCells": sorted(draining),
                    }
                )
        return out

    def _refresh_stranded_locked(self) -> None:
        """Recompute the stranded-gang name set (per-group early exit —
        no per-cell attribution lists). Runs under the lock at every
        applied health transition and at recovery end; the lock-free
        metrics scrape serves this set intersected with the live groups,
        so a scrape never walks placements under any lock."""
        self._stranded_names = {
            name
            for name, g in self.core.affinity_groups.items()
            if any(
                leaf is not None and (not leaf.healthy or leaf.draining)
                for rows in g.physical_placement.values()
                for row in rows
                for leaf in row
            )
        }

    def _check_stranded_locked(self) -> None:
        """Stranded-gang remediation under the eviction policy knob
        (doc/fault-model.md "Elastic gang plane"). Runs after APPLIED
        health transitions only, so a flap held by the damper never
        touches anybody. Always refreshes the stranded gauge first — the
        metrics plane reports stranded gangs whichever policy is
        configured.

        Remediation is migration-aware: actions are planned in preference
        order — opportunistic gangs before any guaranteed gang is
        touched, shrinkable gangs (minMembers headroom) before evictable
        ones, smallest blast radius (affected pods) first — and every
        action is journaled as a ``remediate`` decision record, so the
        ordering is auditable after the fact."""
        self._refresh_stranded_locked()
        if not self.config.stranded_gang_eviction:
            return
        for action in self._remediation_plan_locked():
            rec = self.decisions.begin(
                f"group/{action['group']}", f"group:{action['group']}",
                "remediate",
            )
            rec.group = action["group"]
            rec.vc = action["vc"]
            rec.priority = action["priority"]
            rec.verdict = action["kind"]
            rec.note(
                f"preference order: {'opportunistic' if action['opportunistic'] else 'guaranteed'}, "
                f"{'shrinkable' if action['kind'] == 'shrink' else 'evictable'}, "
                f"blast radius {action['blast']} pod(s)"
            )
            if action["kind"] == "shrink":
                plan = action["plan"]
                rec.note(
                    f"shrink {plan['from_pods']} -> {plan['to_pods']} pods "
                    f"(minMembers {plan['min_members']}, generation "
                    f"{plan['new_gen']}); dropping "
                    f"{sorted(p.key for p in plan['dropped_pods'])}"
                )
                with self._side_effect_lock:
                    self._shrink_in_flight.add(action["group"])
                    self._pending_shrinks.append(plan)
            else:
                self._queue_group_eviction_locked(action["group"], rec)
            self.decisions.commit(rec)
        # Groups that completed/died release their eviction memory. The
        # `_evicted_*` sets are shared with the concurrent flush threads;
        # all read-modify-write maintenance runs under the (innermost)
        # side-effect lock.
        with self._side_effect_lock:
            self._evicted_groups &= set(self.core.affinity_groups)
            self._shrink_in_flight &= set(self.core.affinity_groups)
            live_uids = {
                p.uid
                for g in self.core.affinity_groups.values()
                for pods in g.allocated_pods.values()
                for p in pods
                if p is not None
            }
            self._evicted_pod_uids &= live_uids

    def _queue_group_eviction_locked(self, name: str, rec) -> None:
        """Queue every live pod of a stranded gang for eviction (the
        whole-gang remediation for inelastic or unshrinkable gangs)."""
        with self._side_effect_lock:
            if name in self._evicted_groups:
                return
            g = self.core.affinity_groups.get(name)
            if g is None:
                return
            pods = [
                p
                for pods in g.allocated_pods.values()
                for p in pods
                if p is not None and p.uid not in self._evicted_pod_uids
            ]
            if not pods:
                return
            self._evicted_groups.add(name)
            self._pending_evictions.extend((name, p) for p in pods)
            if rec is not None:
                rec.note(f"evicting {len(pods)} pod(s)")

    def _remediation_plan_locked(self) -> List[Dict]:
        """The ordered remediation actions for the currently-stranded
        gangs: one dict per gang — kind "shrink" (with the prepared plan)
        or "evict" — sorted by the migration-aware preference order."""
        actions: List[Dict] = []
        for srec in self._stranded_groups_locked():
            name = srec["name"]
            g = self.core.affinity_groups.get(name)
            if g is None:
                continue
            with self._side_effect_lock:
                busy = name in self._evicted_groups or (
                    name in self._shrink_in_flight
                )
            if busy:
                continue
            opportunistic = g.virtual_placement is None
            plan = self._plan_shrink_locked(g)
            total = g.total_pods
            if plan is not None:
                actions.append(
                    {
                        "kind": "shrink",
                        "group": name,
                        "vc": str(g.vc),
                        "priority": g.priority,
                        "opportunistic": opportunistic,
                        "blast": len(plan["dropped_pods"]),
                        "plan": plan,
                    }
                )
            else:
                actions.append(
                    {
                        "kind": "evict",
                        "group": name,
                        "vc": str(g.vc),
                        "priority": g.priority,
                        "opportunistic": opportunistic,
                        "blast": total,
                    }
                )
        actions.sort(
            key=lambda a: (
                0 if a["opportunistic"] else 1,
                0 if a["kind"] == "shrink" else 1,
                a["blast"],
                a["priority"],
                a["group"],
            )
        )
        return actions

    def _plan_shrink_locked(self, g) -> Optional[Dict]:
        """Prepare a shrink plan for one stranded gang, or None when the
        gang cannot shrink (no minMembers bound, knob off, not ALLOCATED,
        healthy remainder below the floor, or nothing left to drop). The
        plan carries everything the flush needs: the survivors' new
        annotation values (and the old ones, for rollback), the new
        group-level bind info, and the dropped pods."""
        if (
            not self.config.elastic_gang_shrink
            or g.min_members <= 0
            or g.state != GroupState.ALLOCATED
        ):
            return None
        drop: List[Tuple[int, int]] = []
        keep: List[Tuple[int, int]] = []
        for leaf_num, rows in g.physical_placement.items():
            for pi, row in enumerate(rows):
                stranded = any(
                    leaf is not None and (not leaf.healthy or leaf.draining)
                    for leaf in row
                )
                (drop if stranded else keep).append((leaf_num, pi))
        if not drop or not keep or len(keep) < g.min_members:
            return None
        try:
            member_info, chain = self.core.export_group_bind_info(g)
        except api.WebServerError as e:
            common.log.warning(
                "group %s: cannot regenerate bind info for shrink (%s); "
                "falling back to eviction", g.name, e.message,
            )
            return None
        drop_set = set(drop)
        new_member_info = []
        leaf_nums = sorted(g.physical_placement)
        for mbi_index, mbi in enumerate(member_info):
            leaf_num = leaf_nums[mbi_index]
            kept = [
                pp
                for pi, pp in enumerate(mbi.pod_placements)
                if (leaf_num, pi) not in drop_set
            ]
            if kept:
                new_member_info.append(
                    api.AffinityGroupMemberBindInfo(pod_placements=kept)
                )
        new_gen = g.resize_generation + 1
        counts: Dict[int, int] = {}
        for leaf_num, pi in keep:
            counts[leaf_num] = counts.get(leaf_num, 0) + 1
        group_spec = g.spec_dict(total_pod_nums=counts)
        survivors: List[Pod] = []
        dropped_pods: List[Pod] = []
        for leaf_num, rows in g.allocated_pods.items():
            for pi, p in enumerate(rows):
                if p is None:
                    continue
                ((dropped_pods if (leaf_num, pi) in drop_set else survivors)
                 .append(p))
        patches: List[Tuple[Pod, Dict, Dict]] = []
        spec_obj: Optional[api.PodSchedulingSpec] = None
        for p in survivors:
            try:
                s = extract_pod_scheduling_spec(p)
                info = extract_pod_bind_info(p)
            except api.WebServerError as e:
                common.log.warning(
                    "[%s]: undecodable annotations; shrink of %s falls "
                    "back to eviction: %s", p.key, g.name, e.message,
                )
                return None
            spec_dict = s.to_dict()
            spec_dict["affinityGroup"] = group_spec
            new_info = api.PodBindInfo(
                node=info.node,
                leaf_cell_isolation=list(info.leaf_cell_isolation),
                cell_chain=chain or info.cell_chain,
                affinity_group_bind_info=new_member_info,
                resize_generation=new_gen,
            )
            new_ann = self._resize_annotations(spec_dict, new_info)
            old_ann = {
                k: p.annotations.get(k) for k in new_ann
            }
            patches.append((p, new_ann, old_ann))
            if spec_obj is None:
                spec_obj = api.PodSchedulingSpec.from_dict(spec_dict)
        if spec_obj is None:
            return None  # no survivor pods attached yet: nothing to patch
        return {
            "group": g.name,
            "base_gen": g.resize_generation,
            "new_gen": new_gen,
            "min_members": g.min_members,
            "from_pods": len(keep) + len(drop),
            "to_pods": len(keep),
            "patches": patches,
            "spec": spec_obj,
            "info": api.PodBindInfo(
                cell_chain=chain,
                affinity_group_bind_info=new_member_info,
                resize_generation=new_gen,
            ),
            "dropped_pods": dropped_pods,
        }

    @staticmethod
    def _resize_annotations(
        spec_dict: Dict, info: api.PodBindInfo
    ) -> Dict[str, str]:
        """The annotation rewrite one survivor receives on a resize: the
        reduced/extended scheduling spec, the new-generation bind info,
        and the regenerated TPU env block (gang size changed, so the
        jax.distributed world the env describes changed too)."""
        from ..tpu import env as tpu_env  # late import (framework layering)

        return {
            constants.ANNOTATION_POD_SCHEDULING_SPEC: common.to_json(
                spec_dict
            ),
            constants.ANNOTATION_POD_BIND_INFO: common.to_json(
                info.to_dict()
            ),
            constants.ANNOTATION_POD_TPU_ENV: common.to_yaml_fast(
                tpu_env.pod_tpu_env(info)
            ),
        }

    def _flush_evictions(self) -> None:
        with self._side_effect_lock:
            evictions, self._pending_evictions = self._pending_evictions, []
        for group_name, pod in evictions:
            try:
                self.kube_client.evict_pod(pod)
                with self._side_effect_lock:
                    self._evicted_pod_uids.add(pod.uid)
                self.metrics.observe_stranded_eviction()
                common.log.warning(
                    "[%s]: evicted (stranded gang remediation: the gang "
                    "holds bad or draining cells)", pod.key,
                )
            except Exception as e:  # noqa: BLE001
                # Re-arm the gang so the next flush's stranded re-check
                # retries — only the pods whose delete never landed are
                # re-queued (_evicted_pod_uids).
                with self._side_effect_lock:
                    self._evicted_groups.discard(group_name)
                    self._eviction_retry_pending = True
                common.log.warning(
                    "[%s]: stranded-gang eviction failed (retried at the "
                    "next flush): %s", pod.key, e,
                )

    # -------------- elastic gang plane: shrink + resize sync ----------- #

    def _flush_shrinks(self) -> None:
        with self._side_effect_lock:
            plans, self._pending_shrinks = self._pending_shrinks, []
        for plan in plans:
            try:
                self._execute_shrink(plan)
            finally:
                with self._side_effect_lock:
                    self._shrink_in_flight.discard(plan["group"])

    def _execute_shrink(self, plan: Dict) -> None:
        """Patch-then-apply (doc/fault-model.md "Elastic gang plane"):
        the survivors' annotations are rewritten FIRST — they are the
        durable record of the shrink, and a crash after any subset of
        the patches recovers deterministically through the
        generation-aware replay — the core reshapes second, and the
        dropped members are evicted last. A failed patch rolls the
        already-patched survivors back and aborts the shrink (retried at
        the next flush round)."""
        name = plan["group"]
        patched: List[Tuple[Pod, Dict]] = []
        for pod, new_ann, old_ann in plan["patches"]:
            try:
                self.kube_client.patch_pod_annotations(pod, new_ann)
            except Exception as e:  # noqa: BLE001
                common.log.warning(
                    "[%s]: shrink of %s aborted (survivor patch failed: "
                    "%s); rolling back %d patch(es)",
                    pod.key, name, e, len(patched),
                )
                self._rollback_patches(patched)
                self.metrics.observe_gang_shrink_abort()
                self._journal_resize_outcome(
                    name, "shrink-abort", f"survivor patch failed: {e}"
                )
                with self._side_effect_lock:
                    self._eviction_retry_pending = True
                return
            patched.append((pod, old_ann))
        dropped: Optional[List[Pod]] = None
        with self._lock:
            g = self.core.affinity_groups.get(name)
            if (
                g is not None
                and g.state == GroupState.ALLOCATED
                and g.resize_generation == plan["base_gen"]
            ):
                dropped = self.core.apply_resize(
                    g, plan["spec"], plan["info"], record_event=False
                )
        if dropped is None:
            common.log.warning(
                "group %s changed while its shrink was in flight; rolling "
                "the annotation patches back", name,
            )
            self._rollback_patches(patched)
            self.metrics.observe_gang_shrink_abort()
            self._journal_resize_outcome(
                name, "shrink-abort", "group changed mid-flight"
            )
            return
        # In-memory mirrors of the patched annotations (the informer may
        # not re-deliver these pods for a while; the scheduler's own pod
        # objects must already read as the new generation).
        for pod, new_ann, _old in plan["patches"]:
            pod.annotations.update(new_ann)
            status = self.pod_schedule_statuses.get(pod.uid)
            if status is not None and status.pod is not pod:
                status.pod.annotations.update(new_ann)
        self.metrics.observe_gang_shrink()
        with self._side_effect_lock:
            for p in dropped:
                if p.uid not in self._evicted_pod_uids:
                    self._pending_evictions.append((name, p))
        self._journal_resize_outcome(
            name,
            "shrink-applied",
            f"generation {plan['new_gen']}: {plan['from_pods']} -> "
            f"{plan['to_pods']} pods; evicting {len(dropped)} stranded "
            "pod(s)",
        )

    def _rollback_patches(
        self, patched: List[Tuple[Pod, Dict]]
    ) -> None:
        for pod, old_ann in patched:
            try:
                self.kube_client.patch_pod_annotations(pod, old_ann)
            except Exception as e:  # noqa: BLE001
                self._resize_write_failed = True
                common.log.warning(
                    "[%s]: shrink rollback patch failed (%s); the "
                    "generation-aware replay reconciles the mixed "
                    "annotations at the next recovery", pod.key, e,
                )

    def _journal_resize_outcome(
        self, name: str, verdict: str, note: str
    ) -> None:
        rec = self.decisions.begin(
            f"group/{name}", f"group:{name}", "remediate"
        )
        rec.group = name
        rec.verdict = verdict
        rec.note(note)
        self.decisions.commit(rec)

    def _drain_resize_side_effects(self) -> None:
        """Mutator-exit drain of the core's resize plumbing: replayed
        pods a newer generation shrank away are re-queued for eviction,
        and replay-applied resizes (mixed-generation recovery, grow
        confirms) bump metrics and re-sync surviving pods' stale
        annotations."""
        for pod in self.core.take_resize_orphans():
            try:
                gname = extract_pod_scheduling_spec(pod).affinity_group.name
            except api.WebServerError:
                gname = "unknown"
            with self._side_effect_lock:
                if pod.uid not in self._evicted_pod_uids:
                    self._pending_evictions.append((f"resize:{gname}", pod))
        events = self.core.take_resize_events()
        if not events:
            return
        patches: List[Tuple[Pod, Dict]] = []
        with self._lock:
            for ev in events:
                if ev["kind"] == "shrink":
                    self.metrics.observe_gang_shrink()
                else:
                    self.metrics.observe_gang_grow()
                g = self.core.affinity_groups.get(ev["group"])
                if g is not None:
                    patches.extend(self._resize_sync_patches_locked(g))
        for pod, new_ann in patches:
            try:
                self.kube_client.patch_pod_annotations(pod, new_ann)
                pod.annotations.update(new_ann)
                status = self.pod_schedule_statuses.get(pod.uid)
                if status is not None and status.pod is not pod:
                    status.pod.annotations.update(new_ann)
            except Exception as e:  # noqa: BLE001
                self._resize_write_failed = True
                common.log.warning(
                    "[%s]: resize annotation re-sync failed (advisory — "
                    "the generation-aware replay tolerates stale "
                    "annotations): %s", pod.key, e,
                )

    def _resize_sync_patches_locked(self, g) -> List[Tuple[Pod, Dict]]:
        """Annotation re-syncs for pods whose bind info predates the
        group's current resize generation (advisory: keeps the next
        recovery on the consistent-generation fast path)."""
        try:
            member_info, chain = self.core.export_group_bind_info(g)
        except api.WebServerError:
            return []
        group_spec = g.spec_dict()
        out: List[Tuple[Pod, Dict]] = []
        for rows in g.allocated_pods.values():
            for p in rows:
                if p is None:
                    continue
                try:
                    info = extract_pod_bind_info(p)
                    s = extract_pod_scheduling_spec(p)
                except api.WebServerError:
                    continue
                if (
                    info.resize_generation == g.resize_generation
                    # A grow pod's bind info is already current but its
                    # SPEC still declares the pre-grow member count — it
                    # must be re-synced too, or a restart that replays it
                    # FIRST sizes the group's matrices short of the bind
                    # info's rows.
                    and s.affinity_group is not None
                    and s.affinity_group.total_members == g.total_pods
                ):
                    continue
                spec_dict = s.to_dict()
                spec_dict["affinityGroup"] = group_spec
                new_info = api.PodBindInfo(
                    node=info.node,
                    leaf_cell_isolation=list(info.leaf_cell_isolation),
                    cell_chain=chain or info.cell_chain,
                    affinity_group_bind_info=member_info,
                    resize_generation=g.resize_generation,
                )
                out.append((p, self._resize_annotations(spec_dict, new_info)))
        return out

    def get_health(self) -> Dict:
        """Inspect payload for /v1/inspect/health: applied badness and
        drains (core), held transitions (damper), and stranded gangs."""
        with self._lock:
            payload = self.core.health_snapshot()
            payload["clock"] = self._health_clock
            payload["damper"] = {
                "pendingCount": self._damper.pending_count(),
                "held": self._damper.snapshot(),
            }
            stranded = self._stranded_groups_locked()
            payload["strandedGroups"] = stranded
            payload["strandedGroupCount"] = len(stranded)
            # Piggy-back: this walk just computed the truth — refresh the
            # lock-free gauge the metrics scrape serves.
            self._stranded_names = {r["name"] for r in stranded}
            payload["evictionPolicy"] = (
                "evict" if self.config.stranded_gang_eviction else "surface"
            )
            # _shrink_in_flight is mutated under the side-effect lock by
            # concurrent flushes; snapshot it under the same lock or a
            # resolving shrink crashes the scrape mid-iteration.
            with self._side_effect_lock:
                shrinks_in_flight = sorted(self._shrink_in_flight)
            payload["elastic"] = {
                "shrinkEnabled": bool(self.config.elastic_gang_shrink),
                "shrinksInFlight": shrinks_in_flight,
                "shrinkCount": self.metrics.gang_shrink_count,
                "growCount": self.metrics.gang_grow_count,
            }
            if self.defrag is not None:
                payload["defrag"] = self.defrag.snapshot_locked()
        return payload

    # ------------------------------------------------------------------ #
    # Pod events (reference: scheduler.go:253-360)
    # ------------------------------------------------------------------ #

    def add_pod(self, pod: Pod) -> None:
        if not is_interested(pod):
            return
        # Pre-readiness bound-pod adds ARE the recovery replay (both the
        # recover() path and the informer's initial relist): time each one
        # into the recovery-replay histogram.
        replaying = is_bound(pod) and not self._ready.is_set()
        t0 = time.monotonic() if replaying else 0.0
        top = self._blackbox_top()
        self._enter_mutation()
        try:
            # Chain-scoped like filter: a pod event touches only its own
            # chains' cell state (bound pods: the node's chains via the
            # static index; unbound pods: the status map only), so informer
            # churn no longer stalls every chain's scheduling.
            def locked(sec):
                if is_bound(pod):
                    self._add_bound_pod_locked(pod)
                else:
                    self._admit_unbound(pod)

            if is_bound(pod) and self._snapshot_pending:
                # Delta replay of a bound pod (the map is only non-empty
                # between snapshot import and finish_recovery): a claim
                # conflict releases unconfirmed imports on ARBITRARY
                # chains (_release_pending_snapshot_imports_locked), so
                # the pod's own chain section cannot cover the mutation —
                # take the global order for the replay window.
                with self._locks.section(None):
                    locked(None)
            else:
                self._run_chain_locked(pod, None, locked)
        finally:
            self._exit_mutation()
            if replaying:
                self.metrics.observe_recovery_replay(time.monotonic() - t0)
            if top:
                # Recorded AFTER the verb (all black-box hooks are): a
                # re-anchor triggered at this event captures state that
                # already INCLUDES it, so dropping the event from the
                # fresh window is exact, never lossy.
                self._blackbox_record("record_pod_event", "pod_add", pod)
                self._blackbox_tick()

    def update_pod(self, old: Pod, new: Pod) -> None:
        top = self._blackbox_top()
        self._enter_mutation()
        try:
            self._update_pod(old, new)
        finally:
            self._exit_mutation()
            if top:
                self._blackbox_record("record_pod_update", old, new)
                self._blackbox_tick()

    def _update_pod(self, old: Pod, new: Pod) -> None:
        # An informer may deliver an Update with UID changed when a delete is
        # immediately followed by a create (reference: scheduler.go:265-271).
        if old.uid != new.uid:
            self.delete_pod(old)
            self.add_pod(new)
            return
        if not is_interested(new):
            # Completed pods leave the scheduling view.
            if is_interested(old) or new.uid in self.pod_schedule_statuses:
                self.delete_pod(new)
            return
        record = self.quarantined_pods.get(new.uid)
        if record is not None and new.annotations != record.pod.annotations:
            # The pod changed since it was quarantined (e.g. an operator
            # repaired the bind-info annotation): give replay another try.
            with self._lock:
                self.quarantined_pods.pop(new.uid, None)
            self.add_pod(new)
            return
        old_bound, new_bound = is_bound(old), is_bound(new)
        if not old_bound and new_bound:
            self._add_bound_pod(new)
        elif old_bound and not new_bound:
            # K8s never unbinds a pod in place, so this event is a corrupt or
            # reordered watch stream. The reference asserts here
            # (scheduler.go:280-284) — which kills the informer thread and
            # freezes the scheduling view. Degrade instead: treat it as
            # delete+re-add so the view stays consistent with whatever the
            # apiserver now claims.
            common.log.error(
                "[%s]: Pod updated from bound to unbound (previous bound "
                "node: %s); degrading to delete+re-add", new.key,
                old.node_name,
            )
            self.delete_pod(old)
            self.add_pod(new)

    def delete_pod(self, pod: Pod) -> None:
        top = self._blackbox_top()
        self._enter_mutation()
        try:
            # Chain-scoped (see add_pod): releasing a pod touches only its
            # own chains' cells and group.
            self._run_chain_locked(
                pod, None, lambda sec: self._delete_pod_locked(pod)
            )
        finally:
            self._exit_mutation()
            if top:
                self._blackbox_record("record_pod_event", "pod_delete", pod)
                self._blackbox_tick()

    def _delete_pod_locked(self, pod: Pod) -> None:
        """Body of delete_pod; the caller holds a section covering the
        pod's chains."""
        # A gang that dies without ever registering releases its
        # mixed-SKU claim here (registered groups already dropped it) —
        # but only a claim whose lock set this thread HOLDS is provably
        # not a concurrently-running scheduler's (same rule as the claim
        # override in _claim_group_chains).
        try:
            name = extract_pod_scheduling_spec(pod).affinity_group.name
        except api.WebServerError:
            name = None
        if name:
            with self._side_effect_lock:
                claim = self._group_chain_claims.get(name)
                if claim is not None and self._locks.holds_chains(claim):
                    self._group_chain_claims.pop(name, None)
        # A quarantined pod holds no cell state; just drop the record.
        self.quarantined_pods.pop(pod.uid, None)
        status = self.pod_schedule_statuses.get(pod.uid)
        if status is None:
            return
        try:
            if is_allocated_state(status.pod_state):
                self.core.delete_allocated_pod(status.pod)
            else:
                self.core.delete_unallocated_pod(status.pod)
        except Exception:  # noqa: BLE001
            # A delete that fails half-way must still drop the status:
            # replaying it forever would wedge the informer on one pod
            # (the core logs-and-continues on unknown placements, so
            # anything raising here is unexpected corruption).
            common.log.exception(
                "[%s]: error releasing pod from the core; dropping its "
                "status anyway", pod.key,
            )
        del self.pod_schedule_statuses[pod.uid]

    def _add_bound_pod(self, pod: Pod) -> None:
        if self._snapshot_pending:
            # See add_pod: conflict repair during the delta replay can
            # mutate chains outside this pod's own set.
            with self._locks.section(None):
                self._add_bound_pod_locked(pod)
            return
        self._run_chain_locked(
            pod, None, lambda sec: self._add_bound_pod_locked(pod)
        )

    def _add_bound_pod_locked(self, pod: Pod) -> None:
        status = self.pod_schedule_statuses.get(pod.uid)
        if status is not None and is_allocated_state(status.pod_state):
            if self._snapshot_pending:
                # Delta replay (recovery only — the map is empty in steady
                # state): a snapshot-imported pod is confirmed in O(1) when
                # its live annotations match the snapshot's; a pod that
                # changed between snapshot and crash (annotation rewrite,
                # corrupt bind info) is NOT trusted — release the imported
                # state and replay it from the live annotations below,
                # exactly as full replay would have handled it.
                pending = self._snapshot_pending.pop(pod.uid, None)
                if pending is not None and pending != (
                    self._snapshot_pod_fingerprint(pod)
                ):
                    common.log.warning(
                        "[%s]: changed since the snapshot; replaying from "
                        "live annotations", pod.key,
                    )
                    self._delete_pod_locked(status.pod)
                    status = None
            if status is not None:
                # Already allocated (assume-bind or confirmed snapshot
                # import): the placement never changes again; just confirm
                # Bound (reference: scheduler.go:314-328).
                if status.pod_state != PodState.BOUND:
                    self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                        pod=status.pod, pod_state=PodState.BOUND
                    )
                return
        if pod.uid in self.quarantined_pods:
            # Relists re-deliver quarantined pods every gap repair; the
            # verdict does not change until the pod itself does.
            return
        # Recovery of a pod bound before we started. Validate BEFORE
        # mutating cell state: a corrupt bind-info annotation or a
        # placement gone from the config quarantines this one pod
        # instead of aborting the whole recovery replay
        # (pre-fault-model behavior: raise through recover()).
        if not self._ready.is_set() and self._recovery_mode == "snapshot+delta":
            # A bound pod replayed from annotations during a snapshot
            # recovery: either absent from the snapshot (bound after it was
            # taken) or changed since — the creation/mutation half of the
            # delta replay (the deletion half is
            # _drop_vanished_snapshot_pods).
            self._snapshot_delta_count += 1
        if self._snapshot_pending and self._snapshot_claims_conflict(pod):
            # The live pod claims cells an imported-but-unconfirmed
            # snapshot pod holds: a pod deleted while we were down can
            # hold cells a newer live pod was since bound to (full replay
            # never sees the deleted pod — the import resurrected it; the
            # replay below would silently double-bind the cell and the
            # vanished-pod release would then clobber the live binding).
            # The live cluster supersedes the import: release every
            # unconfirmed imported pod first. The released pods' own live
            # events (later in the relist) re-admit them from annotations
            # — slower, still correct.
            common.log.warning(
                "[%s]: replay conflicts with unconfirmed snapshot imports "
                "(%d pending); releasing them and replaying from "
                "annotations", pod.key, len(self._snapshot_pending),
            )
            self._release_pending_snapshot_imports_locked()
        try:
            self.core.validate_allocated_pod(pod)
            self.core.add_allocated_pod(pod)
        except Exception as e:  # noqa: BLE001
            self._quarantine_pod(pod, e)
            return
        self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
            pod=pod, pod_state=PodState.BOUND
        )

    def _admit_unbound(self, pod: Pod) -> None:
        """Lock-free body shared by the informer add_pod path and the
        auto-admit path — both inside the pod's CHAIN section, which must
        not widen to the global order (lock-sharding contract)."""
        if pod.uid in self.pod_schedule_statuses:
            return
        self.core.add_unallocated_pod(pod)
        self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
            pod=pod, pod_state=PodState.WAITING
        )

    # ------------------------------------------------------------------ #
    # Admission + bind validation (reference: scheduler.go:362-466)
    # ------------------------------------------------------------------ #

    def _admission_check(
        self, uid: str, pod: Optional[Pod] = None
    ) -> PodScheduleStatus:
        """Only live unbound hived pods may be scheduled
        (reference: scheduler.go:364-383)."""
        status = self.pod_schedule_statuses.get(uid)
        if status is None and self.auto_admit and pod is not None:
            self._admit_unbound(pod)
            status = self.pod_schedule_statuses.get(uid)
        if status is None:
            raise api.bad_request(
                "Pod does not exist, completed or has not been informed to "
                "the scheduler"
            )
        if status.pod_state == PodState.BOUND:
            raise api.bad_request(
                f"Pod has already been bound to node {status.pod.node_name}"
            )
        return status

    def _validate_pod_bind_info(
        self, bind_info: api.PodBindInfo, suggested_nodes: List[str]
    ) -> Optional[str]:
        """Detect a probably-stale decision: target node gone, or outside the
        default scheduler's suggestions (reference: scheduler.go:385-421)."""
        node = bind_info.node
        if node not in self.nodes:
            return (
                f"The scheduling algorithm decided to bind on node {node}, but "
                f"the node does not exist or has not been informed to the "
                f"scheduler"
            )
        if node not in suggested_nodes:
            return (
                f"The scheduling algorithm decided to bind on node {node} but "
                f"the node is not within the selected nodes from the K8s "
                f"default scheduler"
            )
        return None

    def _should_force_bind(
        self, status: PodScheduleStatus, suggested_nodes: List[str]
    ) -> bool:
        """Keep binding regardless of potentially-stale decisions: after
        enough failed attempts, or as soon as the decision looks invalid,
        bypass the default scheduler (reference: scheduler.go:423-466; the
        long comment there argues why insisting is safe: a truly-bad bind
        fails the pod naturally and K8s retries it)."""
        if status.pod_bind_attempts >= self.config.force_pod_bind_threshold:
            common.log.warning(
                "[%s]: Will force bind Pod: binding tried %d times, reaching "
                "ForcePodBindThreshold %d",
                status.pod.key,
                status.pod_bind_attempts,
                self.config.force_pod_bind_threshold,
            )
            return True
        assert status.pod_schedule_result is not None
        bind_info = status.pod_schedule_result.pod_bind_info
        assert bind_info is not None
        err = self._validate_pod_bind_info(bind_info, suggested_nodes)
        if err is not None:
            common.log.warning("[%s]: Will force bind Pod: %s", status.pod.key, err)
            return True
        return False

    def _force_bind(self, binding_pod: Pod) -> None:
        """Shadow of bind_routine bypassing the default scheduler
        (reference: scheduler.go:471-483)."""
        try:
            self.bind_routine(
                ei.ExtenderBindingArgs(
                    pod_name=binding_pod.name,
                    pod_namespace=binding_pod.namespace,
                    pod_uid=binding_pod.uid,
                    node=binding_pod.node_name,
                )
            )
        except Exception as e:  # noqa: BLE001
            # One force-bind failure — protocol error OR kube transport
            # error — is ignorable; it will be retried on the next filter
            # round (reference: HandleWebServerPanic recovers everything).
            common.log.warning(
                "[%s]: forceBindExecutor: %s", binding_pod.key, e
            )

    # ------------------------------------------------------------------ #
    # Pending-pod plane: the negative-filter (WAIT) cache
    # (doc/hot-path.md "Pending-pod plane")
    # ------------------------------------------------------------------ #

    def _suggested_token(self, node_names: List[str]) -> Tuple[int, int]:
        """O(1) token for a reused node-name list (object-identity memo),
        O(n) tuple hash for a fresh one. Two calls with the same list
        CONTENTS in the same order produce the same token; a reordered or
        changed set produces a different one — the compare direction is
        conservative (a spurious mismatch just runs the full filter)."""
        memo = self._suggested_token_memo
        if (
            memo is not None
            and memo[0] is node_names
            and memo[1] == len(node_names)
        ):
            return memo[2]
        token = (len(node_names), hash(tuple(node_names)))
        self._suggested_token_memo = (node_names, len(node_names), token)
        return token

    def _wait_cache_store(
        self, key: str, spec, cert: Dict, wait_reason: str
    ) -> None:
        """Memoize a WAIT verdict (called inside the filter's chain
        section, AFTER schedule() returned — the certificate's vector
        reflects exactly the state the descent read)."""
        entry = {
            "cert": cert,
            "waitReason": wait_reason,
            "vc": str(spec.virtual_cluster),
            "priority": spec.priority,
            "leafCellType": str(spec.leaf_cell_type or ""),
            "leafCellNumber": spec.leaf_cell_number,
            "group": (
                spec.affinity_group.name
                if spec.affinity_group is not None
                else ""
            ),
        }
        with self._wait_cache_lock:
            cache = self._wait_cache
            if key not in cache and len(cache) >= (
                self.config.wait_cache_capacity
            ):
                # Bounded FIFO eviction (no LRU reordering: hits must
                # stay lock-free dict reads).
                cache.pop(next(iter(cache)), None)
            cache[key] = entry

    def _wait_cache_drop(self, key: str) -> None:
        if key and self._wait_cache:
            with self._wait_cache_lock:
                self._wait_cache.pop(key, None)

    def _wait_cache_clear(self) -> None:
        """Wholesale invalidation for state restores that bypass the
        epoch-bumping cell mutators (snapshot import / pre-apply discard
        / recovery replay)."""
        if self._wait_cache:
            with self._wait_cache_lock:
                self._wait_cache.clear()

    @staticmethod
    def _spec_cache_key(spec_text: str, leaf_types) -> str:
        """Wait-cache key: the spec identity, plus the sweep-chunk
        restriction when one applies — a chunk's WAIT certificate
        answers only its own restricted scan, and one spec can be probed
        under several different chunks of the shards frontend's
        leaf-type-granular sweep (one cache entry per chunk keeps the
        O(1) repeated-rejection answer the cache exists for)."""
        if not spec_text or leaf_types is None:
            return spec_text
        return spec_text + "\x00" + "\x1f".join(leaf_types)

    def _try_fast_wait(
        self, args: ei.ExtenderArgs, leaf_types=None
    ) -> Optional[ei.ExtenderFilterResult]:
        """The repeated-rejection fast path: when this spec identity's
        last verdict was WAIT and its rejection certificate's version
        vector is unchanged, answer WAIT with one vector compare — no
        spec decode, no suggested-set build, no lock section, no
        placement descent. None means: take the full path (cache miss,
        vector moved, or the pod is not plainly WAITING — BINDING pods
        must insist on their bind, unknown pods must see the admission
        check). The decision journal still records the attempt (with the
        certificate), so explainability survives the shortcut."""
        pod = args.pod
        key = self._spec_cache_key(
            pod.annotations.get(
                constants.ANNOTATION_POD_SCHEDULING_SPEC, ""
            ),
            leaf_types,
        )
        if not key:
            return None
        entry = self._wait_cache.get(key)
        if entry is None:
            return None
        status = self.pod_schedule_statuses.get(pod.uid)
        if status is None:
            if not self.auto_admit:
                return None  # the admission check must reject it
        elif status.pod_state != PodState.WAITING:
            return None
        cert = entry["cert"]
        if status is None:
            # Auto-admit (sims/benches): register the pod WAITING like
            # the full path's admission check would, so the status map
            # is identical cache-on and cache-off. Single dict insert +
            # a no-op core call — safe without the chain section (the
            # full path's own WAIT status write is the same GIL-atomic
            # insert; auto-admit callers drive each pod from one
            # thread). The status carries no pod_schedule_result —
            # nothing reads that field for WAITING pods.
            self._admit_unbound(pod)
        if cert.get("gate") == GATE_APISERVER_OUTAGE:
            # Weather certificate (gate + weather-epoch vector, no core
            # version vector): servable while the sky is still black and
            # the epoch unchanged — any transition (heal included) bumps
            # the epoch, so the verdict self-invalidates.
            if not self.weather_vane.certificate_current(cert):
                self._wait_cache_drop(key)
                return None
        elif "suggested" not in cert:
            # A vector-shaped certificate of an unknown gate (e.g. a
            # shardDown cert that leaked across layers): never servable.
            self._wait_cache_drop(key)
            return None
        else:
            if cert["suggested"] is not None and cert["suggested"] != (
                self._suggested_token(args.node_names)
            ):
                return None
            if not self.core.certificate_current(cert):
                return None
        wait_reason = entry["waitReason"]
        tr = self.tracer.trace("filter", pod=pod.key)
        rec = self.decisions.begin(
            pod.key, pod.uid, "filter",
            trace_id=tr.trace_id if tr else None,
        )
        rec.lock_chains = "waitCache"
        rec.vc = entry["vc"]
        rec.priority = entry["priority"]
        rec.leaf_cell_type = entry["leafCellType"]
        rec.leaf_cell_number = entry["leafCellNumber"]
        rec.group = entry["group"]
        rec.note("served from the wait cache (certificate unchanged)")
        rec.verdict_wait(wait_reason, certificate=cert)
        self.decisions.commit(rec)
        if tr:
            tr.add_span("waitCache", 0.0)
            tr.finish(outcome="wait")
        if self.config.waiting_pod_scheduling_block_ms > 0:
            # The FIFO-approximation knob blocks WAIT responses; a cached
            # WAIT is still a WAIT response.
            time.sleep(self.config.waiting_pod_scheduling_block_ms / 1e3)
        return ei.ExtenderFilterResult(
            failed_nodes={constants.COMPONENT_NAME: wait_reason}
        )

    def _outage_wait(
        self, args: ei.ExtenderArgs, leaf_types=None
    ) -> Optional[ei.ExtenderFilterResult]:
        """Blackout filter short-circuit: a pod that would need a NEW
        placement waits with a weather-epoch certificate instead of
        descending (no assume-bind whose bind write cannot land). The
        certificate is stored in the negative-filter cache, so the
        outage retry storm this verdict provokes costs one lock-free
        vector compare per re-filter (_try_fast_wait). Returns None for
        pods the full path must answer (BINDING/BOUND insists, unknown
        pods under production admission)."""
        pod = args.pod
        status = self.pod_schedule_statuses.get(pod.uid)
        if status is None:
            if not self.auto_admit:
                return None  # the admission check must reject it
        elif status.pod_state != PodState.WAITING:
            return None
        if status is None:
            self._admit_unbound(pod)
        cert = self.weather_vane.certificate()
        reason = (
            "apiserver blackout (weather epoch "
            f"{cert['vector']['weatherEpoch']}): new placements deferred "
            "until the control plane heals"
        )
        key = self._spec_cache_key(
            pod.annotations.get(
                constants.ANNOTATION_POD_SCHEDULING_SPEC, ""
            ),
            leaf_types,
        )
        spec = None
        if key and self.wait_cache_enabled:
            try:
                spec = extract_pod_scheduling_spec(pod)
            except api.WebServerError:
                spec = None
            if spec is not None:
                self._wait_cache_store(key, spec, cert, reason)
        rec = self.decisions.begin(pod.key, pod.uid, "filter")
        rec.lock_chains = "apiserverOutage"
        if spec is not None:
            rec.set_spec(spec)
        rec.note("degraded WAIT: apiserver blackout")
        rec.verdict_wait(reason, certificate=cert)
        self.decisions.commit(rec)
        self.metrics.observe_outage_wait()
        if self.config.waiting_pod_scheduling_block_ms > 0:
            time.sleep(self.config.waiting_pod_scheduling_block_ms / 1e3)
        return ei.ExtenderFilterResult(
            failed_nodes={constants.COMPONENT_NAME: reason}
        )

    # ------------------------------------------------------------------ #
    # Filter (reference: scheduler.go:485-587)
    # ------------------------------------------------------------------ #

    def filter_routine(
        self,
        args: ei.ExtenderArgs,
        leaf_types: Optional[Tuple[str, ...]] = None,
        trace_parent: Optional[int] = None,
    ) -> ei.ExtenderFilterResult:
        """``leaf_types`` restricts an untyped pod's any-leaf-type scan to
        a sweep chunk (the shards frontend's leaf-type-granular sweep;
        see core.schedule). Restricted probes use the wait cache under a
        CHUNK-QUALIFIED key (_spec_cache_key): a chunk's certificate
        covers only its own restricted scan, and one spec can carry
        several chunks. ``trace_parent`` is the frontend's trace id when
        this call was routed over the shard pipe protocol — the local
        trace commits as its child (causal cross-shard stitching)."""
        top = self._blackbox_top()
        self._enter_mutation()
        result: Optional[ei.ExtenderFilterResult] = None
        err = ""
        try:
            result = self._filter_routine(args, leaf_types, trace_parent)
            return result
        except api.WebServerError as e:
            err = e.message
            raise
        finally:
            self._exit_mutation()
            if top:
                rec = self.recorder
                if rec is not None:
                    try:
                        self._record_filter_outcome(rec, args, result, err)
                    except Exception:  # noqa: BLE001 — never raise
                        common.log.exception("flight-recorder hook failed")
                self._blackbox_tick()

    def _record_filter_outcome(self, rec, args, result, err: str) -> None:
        """Record the verb with the SHARED outcome classification
        (recorder.filter_outcome — one implementation for both
        frontends), plus the framework-only extras: the error message
        and, on binds, the raw isolation annotation (recorded verbatim;
        the fingerprint compares it as an opaque token, so the hot path
        never parses it)."""
        pod = args.pod
        outcome = recorder_mod.filter_outcome(result)
        node = ""
        leaf_cells = None
        if outcome == "bind":
            node = result.node_names[0]
            status = self.pod_schedule_statuses.get(pod.uid)
            if status is not None and status.pod is not None:
                leaf_cells = status.pod.annotations.get(
                    constants.ANNOTATION_POD_LEAF_CELL_ISOLATION
                )
        rec.record_filter(
            pod, args.node_names, outcome, node=node,
            leaf_cells=leaf_cells,
            error=err if outcome == "error" else "",
        )

    def _filter_routine(
        self,
        args: ei.ExtenderArgs,
        leaf_types: Optional[Tuple[str, ...]] = None,
        trace_parent: Optional[int] = None,
    ) -> ei.ExtenderFilterResult:
        start = time.monotonic()
        pod = args.pod
        if self.wait_cache_enabled:
            fast = self._try_fast_wait(args, leaf_types)
            if fast is not None:
                self.metrics.observe_fast_wait()
                self.metrics.observe_filter(
                    time.monotonic() - start, "wait", 0.0, None
                )
                return fast
        if self.weather_vane.state() == weather_mod.BLACKOUT:
            # Degraded serving (doc/fault-model.md "Control-plane weather
            # plane"): pods needing a NEW placement defer with a
            # weather-epoch WAIT certificate — assume-binding cells whose
            # bind write cannot land would churn allocations for nothing.
            # BINDING/BOUND pods fall through: the insist path answers
            # off the projection without a durable write.
            degraded = self._outage_wait(args, leaf_types)
            if degraded is not None:
                self.metrics.observe_filter(
                    time.monotonic() - start, "wait", 0.0, None
                )
                return degraded
        # Observability plane: a (sampled) span trace for the whole verb,
        # and an (always-on) decision record begun inside the section —
        # where the acquired lock scope is known (doc/observability.md).
        # A routed call carries the frontend's trace id as the parent so
        # the merged multi-shard ring stitches causally.
        tr = self.tracer.trace("filter", pod=pod.key, parent=trace_parent)
        # Outside the lock: everything that is a pure function of the request
        # — the YAML spec decode+validation and the suggested-node set build
        # are per-request O(spec) / O(cluster) work that previously sat inside
        # the critical section, serializing concurrent filter calls behind
        # it. (Result serialization is already outside: the webserver encodes
        # the returned ExtenderFilterResult after this method exits.) A spec
        # error is captured, not raised: the BINDING insist path never reads
        # the spec, and a bound pod whose annotation was corrupted after the
        # decision must still get its bind re-affirmed (old behavior).
        spec = spec_error = None
        try:
            spec = extract_pod_scheduling_spec(pod)
        except api.WebServerError as e:
            spec_error = e
        suggested_set = set(args.node_names)
        # The certificate's suggested-set token is pure request data too:
        # hash it here, not under the chain locks (for fresh per-request
        # lists — the webserver — it is O(fleet) like the set build).
        suggested_token = (
            None
            if spec is None or spec.ignore_k8s_suggested_nodes
            else self._suggested_token(args.node_names)
        )

        # Chain-scoped critical section: filters for disjoint chains run
        # concurrently (spec parse above and result serialization in the
        # webserver are already outside). Each section measures its own
        # lock wait, per chain (lockWaitByChain in the metrics); a widened
        # retry contributes its wait too.
        sections: List = []

        def locked(sec):
            sections.append(sec)
            rec = self.decisions.begin(
                pod.key, pod.uid, "filter",
                trace_id=tr.trace_id if tr else None,
            )
            rec.lock_chains = self._lock_scope(sec)
            try:
                return self._filter_locked(
                    args, spec, spec_error, suggested_set, sec,
                    suggested_token, leaf_types,
                )
            except api.WebServerError as e:
                rec.verdict_error(e.message)
                raise
            finally:
                self.decisions.commit(rec)

        outcome = "error"
        core_s = None
        try:
            with tracing.use(tr):
                result, outcome, core_s = self._run_chain_locked(
                    pod, spec, locked
                )
        finally:
            # finally, not except: the trace of a CRASHING filter (any
            # exception, not just protocol errors) is exactly the trace a
            # debugging session needs in the ring.
            if tr:
                for s in sections:
                    tr.add_span(
                        "lockWait", s.wait_s, chains=self._lock_scope(s)
                    )
                if core_s is not None:
                    tr.add_span("coreSchedule", core_s)
                tr.finish(outcome=outcome)
        lock_wait = sum(s.wait_s for s in sections)
        self.metrics.observe_filter(
            time.monotonic() - start, outcome, lock_wait, core_s
        )
        return result

    def _lock_scope(self, sec) -> object:
        """Display form of a section's lock scope: the chain-name list, or
        "global" when it covers every chain."""
        return (
            "global"
            if sec.keys == self._locks.all_keys
            else [str(k) for k in sec.keys]
        )

    def _filter_locked(self, args, spec, spec_error, suggested_set,
                       sec=None, suggested_token=None, leaf_types=None):
        pod = args.pod
        suggested_nodes = args.node_names
        rec = self.decisions.current()
        spec_key = self._spec_cache_key(
            pod.annotations.get(
                constants.ANNOTATION_POD_SCHEDULING_SPEC, ""
            ),
            leaf_types,
        )

        status = self._admission_check(pod.uid, pod)
        if status.pod_state == PodState.BINDING:
            # Insist on the previous bind result: binding is idempotent and
            # the algorithm has already assumed it allocated
            # (reference: scheduler.go:497-510).
            binding_pod = status.pod
            status.pod_bind_attempts += 1
            if rec is not None:
                rec.verdict_insist(binding_pod.node_name)
            if self._should_force_bind(status, suggested_nodes):
                self._spawn(lambda: self._force_bind(binding_pod))
            return (
                ei.ExtenderFilterResult(node_names=[binding_pod.node_name]),
                "bind",
                None,  # insist path: the core never ran
            )

        # podState is Waiting or Preempting: carry out a new scheduling.
        if spec_error is not None:
            raise spec_error
        core_t0 = time.monotonic()
        result = self.core.schedule(
            pod,
            suggested_nodes,
            SchedulingPhase.FILTERING,
            spec=spec,
            suggested_set=suggested_set,
            leaf_types=leaf_types,
        )
        core_s = time.monotonic() - core_t0

        if result.pod_bind_info is not None:
            binding_pod = new_binding_pod(pod, result.pod_bind_info)
            # Assume-bind: mark allocated NOW so the next pod schedules
            # against updated state without waiting for the K8s bind
            # round-trip (reference: scheduler.go:518-530). Batched gang
            # admission: hand the decoded spec, the just-generated bind
            # info, and the pod's slot index straight back to the core —
            # the reference re-decodes the annotations it serialized one
            # line earlier, once per pod of the gang.
            self.core.add_allocated_pod(
                binding_pod,
                spec=spec,
                bind_info=result.pod_bind_info,
                pod_index=result.pod_index,
            )
            new_status = PodScheduleStatus(
                pod=binding_pod,
                pod_state=PodState.BINDING,
                pod_schedule_result=result,
            )
            self.pod_schedule_statuses[pod.uid] = new_status
            if self._should_force_bind(new_status, suggested_nodes):
                self._spawn(lambda: self._force_bind(binding_pod))
            common.log.info("[%s]: Pod is binding to %s", pod.key, binding_pod.node_name)
            if rec is not None:
                rec.verdict_bind(
                    binding_pod.node_name,
                    result.pod_bind_info.leaf_cell_isolation,
                )
            if self.wait_cache_enabled:
                # The spec schedules now; a memoized WAIT is moot (its
                # vector is stale anyway — the bind bumped the epochs).
                self._wait_cache_drop(spec_key)
            return (
                ei.ExtenderFilterResult(node_names=[binding_pod.node_name]),
                "bind",
                core_s,
            )

        if result.pod_preempt_info is not None:
            # FailedNodes tell the default scheduler preemption may help
            # (reference: scheduler.go:540-559).
            failed_nodes: Dict[str, str] = {}
            for victim in result.pod_preempt_info.victim_pods:
                node = victim.node_name
                if node not in failed_nodes:
                    failed_nodes[node] = (
                        f"node({node}) has preemptible Pods: {victim.key}"
                    )
                else:
                    failed_nodes[node] += ", " + victim.key
            common.log.info(
                "[%s]: Pod is waiting for preemptRoutine: %s", pod.key, failed_nodes
            )
            if rec is not None:
                rec.verdict_preempt(result.pod_preempt_info.victim_pods)
            if self.wait_cache_enabled:
                self._wait_cache_drop(spec_key)
            return (
                ei.ExtenderFilterResult(failed_nodes=failed_nodes),
                "preempt",
                core_s,
            )

        self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
            pod=pod, pod_state=PodState.WAITING, pod_schedule_result=result
        )
        # Optionally block to achieve better FIFO (reference: scheduler.go:567-571).
        if self.config.waiting_pod_scheduling_block_ms > 0:
            time.sleep(self.config.waiting_pod_scheduling_block_ms / 1e3)
        wait_reason = "Pod is waiting for preemptible or free resource to appear"
        if result.pod_wait_info is not None and result.pod_wait_info.reason:
            wait_reason += ": " + result.pod_wait_info.reason
        common.log.info("[%s]: %s", pod.key, wait_reason)
        # Pending-pod plane: emit the rejection certificate — the failed
        # gate plus the version vector this attempt read, captured HERE,
        # inside the section, after schedule() returned (the descent's own
        # mutations, e.g. a reverted lazy preempt, already bumped the
        # epochs the vector records). The certificate rides the decision
        # record (the what-if plane's input) and keys the wait cache.
        cert = None
        if spec is not None:
            chains = (
                sec.keys if sec is not None
                else tuple(self.core.chain_epochs)
            )
            cert = self.core.rejection_certificate(
                spec,
                result.pod_wait_info.reason
                if result.pod_wait_info is not None
                else "",
                chains,
                # Hashed pre-lock in _filter_routine (None when the spec
                # ignores suggested nodes).
                suggested_token,
            )
        if rec is not None:
            rec.verdict_wait(wait_reason, certificate=cert)
        if cert is not None and self.wait_cache_enabled and spec_key:
            # Restricted (sweep-chunk) probes store under their chunk-
            # qualified key — see _spec_cache_key.
            self._wait_cache_store(spec_key, spec, cert, wait_reason)
        # Fake FailedNodes expose the wait reason alongside the default
        # scheduler's own reasons (reference: scheduler.go:573-585).
        return (
            ei.ExtenderFilterResult(
                failed_nodes={constants.COMPONENT_NAME: wait_reason}
            ),
            "wait",
            core_s,
        )

    # ------------------------------------------------------------------ #
    # Bind (reference: scheduler.go:589-627)
    # ------------------------------------------------------------------ #

    def bind_routine(
        self,
        args: ei.ExtenderBindingArgs,
        trace_parent: Optional[int] = None,
    ) -> ei.ExtenderBindingResult:
        """Idempotent: may be called multiple times for the same pod; once a
        pod is allocated its placement never changes."""
        # top distinguishes an extender-driven bind from the sync
        # force-bind re-entry (which runs INSIDE a filter's mutation
        # bracket, possibly holding a chain section — the auditor's
        # global acquisition must never run there).
        top = self._blackbox_top()
        ok = False
        try:
            result = self._bind_routine(args, trace_parent)
            ok = True
            return result
        finally:
            if top:
                rec = self.recorder
                if rec is not None:
                    try:
                        rec.record_bind(
                            args.pod_name, args.pod_namespace,
                            args.pod_uid, args.node, ok,
                        )
                    except Exception:  # noqa: BLE001
                        common.log.exception(
                            "flight-recorder hook failed"
                        )
                self._blackbox_tick()

    def _bind_routine(
        self,
        args: ei.ExtenderBindingArgs,
        trace_parent: Optional[int] = None,
    ) -> ei.ExtenderBindingResult:
        # Validate under the lock, but perform the apiserver write outside
        # it: a bind is a full network RTT, and holding the exclusive lock
        # through it would serialize gang binds and stall all filtering
        # (the reference holds only a read lock here, scheduler.go:595-596).
        # Safe because a BINDING pod's placement is immutable AND the
        # Binding carries the pod UID as an apiserver precondition
        # (kube.py bind_pod), so a delete+recreate of the same pod name
        # between validation and write cannot receive the stale bind.
        # Chain-scoped: the validation only reads this pod's status, so
        # the section for its spec's chains suffices (and is what the
        # sync force-bind executor already holds when it re-enters here).
        peek = self.pod_schedule_statuses.get(args.pod_uid)
        chains = (
            self._pod_lock_chains(peek.pod) if peek is not None else None
        )
        with self._locks.section(chains):
            status = self._admission_check(args.pod_uid)
            if status.pod_state != PodState.BINDING:
                raise api.bad_request(
                    f"Pod cannot be bound without a scheduling placement: Pod "
                    f"current scheduling state {status.pod_state.value}, "
                    f"received node {args.node}"
                )
            binding_pod = status.pod
            if binding_pod.node_name != args.node:
                raise api.bad_request(
                    f"Pod binding node mismatch: expected "
                    f"{binding_pod.node_name}, received {args.node}"
                )
        # HA fencing (doc/fault-model.md "HA and snapshot recovery plane"):
        # a deposed leader must never write a bind — the new leader owns
        # the cluster state, and a stale bind would allocate cells the new
        # leader believes free. Checked immediately before the write; the
        # residual window (lease expiring mid-write) is closed by the bind
        # UID precondition + lease duration >> write timeout (see the
        # split-brain argument in the doc).
        if not self.is_leader():
            self.metrics.observe_deposed_bind_refused()
            raise api.WebServerError(
                503,
                "not the leader: bind refused (lease lost or standby); "
                "the active leader will re-schedule this pod",
            )
        # Weather fence (doc/fault-model.md "Control-plane weather
        # plane"): during an apiserver blackout the Binding POST cannot
        # land — refuse RETRIABLY (503, apiserverOutage) before spending
        # the full retry budget per bind. The allocation is kept: the
        # next filter round insists on the same placement, and the
        # default scheduler retries the bind after the weather clears.
        if self.weather_vane.state() == weather_mod.BLACKOUT:
            self.metrics.observe_outage_bind_refused()
            raise api.WebServerError(
                503,
                "apiserverOutage: bind refused retriably (apiserver "
                "blackout, weather epoch "
                f"{self.weather_vane.epoch}); the placement is kept and "
                "the bind will be retried after the weather clears",
            )
        tr = self.tracer.trace(
            "bind", pod=binding_pod.key, parent=trace_parent
        )
        t0 = time.monotonic()
        try:
            self.kube_client.bind_pod(binding_pod)
        finally:
            dt = time.monotonic() - t0
            # The bind-write histogram includes any retry backoff the
            # RetryingKubeClient spent inside the write.
            self.metrics.observe_bind_write(dt)
            if tr:
                tr.add_span("bindWrite", dt, node=binding_pod.node_name)
                tr.finish()
        return ei.ExtenderBindingResult()

    def handle_terminal_bind_failure(self, binding_pod: Pod) -> None:
        """The bind write failed terminally (pod gone: 404, or replaced: 409
        UID-precondition): the assume-bind allocation would hold the gang's
        cells forever, since no informer DELETE will ever arrive for a pod
        that was never bound. Release it; if the pod still exists unbound,
        the default scheduler re-filters it and it is re-admitted cleanly
        (called by RetryingKubeClient, outside the scheduler lock — except
        the sync force-bind test path, which re-enters holding the pod's
        chain section; the section here is the same set, so it must NOT be
        the global guard or it would widen)."""
        top = self._blackbox_top()
        released = False
        self._enter_mutation()
        try:
            with self._locks.section(self._pod_lock_chains(binding_pod)):
                status = self.pod_schedule_statuses.get(binding_pod.uid)
                if status is None or status.pod_state != PodState.BINDING:
                    # Never allocated, or already confirmed Bound (the
                    # informer owns the lifecycle from there).
                    return
                common.log.error(
                    "[%s]: releasing allocation after terminal bind failure "
                    "(node %s)", binding_pod.key, binding_pod.node_name,
                )
                self._delete_pod_locked(status.pod)
                released = True
        finally:
            self._exit_mutation()
            if released and self.recorder is not None:
                # The release is driven by a kube-write FAILURE the replay
                # cannot reproduce (its kube client never fails): record
                # it as the pod delete it is, so the replayed state
                # converges. The nested (sync force-bind) re-entry cannot
                # record mid-verb — re-anchor instead of silently leaving
                # a window whose replay would keep the allocation.
                if top:
                    self._blackbox_record(
                        "record_pod_event", "pod_delete", binding_pod
                    )
                    self._blackbox_tick()
                else:
                    self.recorder.force_reanchor()

    # ------------------------------------------------------------------ #
    # Preempt (reference: scheduler.go:629-721)
    # ------------------------------------------------------------------ #

    def preempt_routine(
        self,
        args: ei.ExtenderPreemptionArgs,
        trace_parent: Optional[int] = None,
    ) -> ei.ExtenderPreemptionResult:
        top = self._blackbox_top()
        self._enter_mutation()
        start = time.monotonic()
        tr = self.tracer.trace(
            "preempt", pod=args.pod.key, parent=trace_parent
        )
        sections: List = []
        preempt_result: Optional[ei.ExtenderPreemptionResult] = None
        try:
            # Chain-scoped like filter: preempt probes and commits touch
            # only the pod's spec-derived chains (victims overlap the
            # preemptor's own placement by construction).
            spec = None
            try:
                spec = extract_pod_scheduling_spec(args.pod)
            except api.WebServerError:
                pass

            def locked(sec):
                sections.append(sec)
                rec = self.decisions.begin(
                    args.pod.key, args.pod.uid, "preempt",
                    trace_id=tr.trace_id if tr else None,
                )
                rec.lock_chains = self._lock_scope(sec)
                try:
                    return (
                        self._preempt_locked(args),
                        self._preempt_annotation_patch(args.pod),
                    )
                except api.WebServerError as e:
                    rec.verdict_error(e.message)
                    raise
                finally:
                    self.decisions.commit(rec)

            with tracing.use(tr):
                result, patch = self._run_chain_locked(args.pod, spec, locked)
            if patch is not None:
                # Checkpoint the reservation onto the preemptor pod OUTSIDE
                # the lock (it is a kube write): a crash between the
                # reservation and this patch simply loses the reservation —
                # exactly the pre-PR behavior — while a crash after it
                # recovers the Reserving/Reserved state. Advisory, so a
                # failed patch only logs.
                pod, value = patch
                try:
                    self.kube_client.patch_pod_annotations(
                        pod, {constants.ANNOTATION_POD_PREEMPT_INFO: value}
                    )
                    pod.annotations[
                        constants.ANNOTATION_POD_PREEMPT_INFO
                    ] = value
                except Exception as e:  # noqa: BLE001
                    common.log.warning(
                        "[%s]: preempt-info checkpoint patch failed (the "
                        "reservation will not survive a crash): %s",
                        pod.key, e,
                    )
            preempt_result = result
            return result
        finally:
            if tr:
                for s in sections:
                    tr.add_span(
                        "lockWait", s.wait_s, chains=self._lock_scope(s)
                    )
                tr.finish()
            self.metrics.observe_preempt_routine(time.monotonic() - start)
            self._exit_mutation()
            if top:
                self._blackbox_record_preempt(args, preempt_result)
                self._blackbox_tick()

    def _preempt_annotation_patch(self, pod: Pod):
        """Under the lock: decide whether the pod needs its preempt-info
        annotation (re)written — it is PREEMPTING and its group's current
        reservation differs from what the pod already carries."""
        status = self.pod_schedule_statuses.get(pod.uid)
        if status is None or status.pod_state != PodState.PREEMPTING:
            return None
        try:
            s = extract_pod_scheduling_spec(pod)
            payload = self.core.get_preempt_info_payload(s.affinity_group.name)
        except api.WebServerError:
            return None
        if payload is None:
            return None
        # The pod's checkpoint is being (re)affirmed: drop any clear a
        # cancellation queued for it earlier in THIS round (core.schedule
        # cancels a stale reservation and immediately recreates it in one
        # call) — the exit-time flush must not erase a live checkpoint.
        # Rebind under the side-effect lock: concurrent chain sections
        # extend this list and flushes swap it.
        with self._side_effect_lock:
            self._pending_annotation_clears = [
                p for p in self._pending_annotation_clears if p.uid != pod.uid
            ]
        value = common.to_json(payload)
        if pod.annotations.get(constants.ANNOTATION_POD_PREEMPT_INFO) == value:
            return None
        return status.pod, value

    def _preempt_locked(
        self, args: ei.ExtenderPreemptionArgs
    ) -> ei.ExtenderPreemptionResult:
        # Caller (preempt_routine via _run_chain_locked) holds the section.
        pod = args.pod
        rec = self.decisions.current()
        # In the Preempting phase the candidate nodes are those where the
        # default scheduler found lower-priority victims.
        suggested_nodes = list(args.node_name_to_meta_victims.keys())

        status = self._admission_check(pod.uid, pod)
        if status.pod_state == PodState.BINDING:
            raise api.bad_request(
                f"Pod has already been binding to node {status.pod.node_name}"
            )

        # Whether Waiting or Preempting, schedule afresh: a previous
        # preemption result may be stale (reference: scheduler.go:655-668).
        core_t0 = time.monotonic()
        result = self.core.schedule(
            pod, suggested_nodes, SchedulingPhase.PREEMPTING
        )
        tracing.add_span("coreSchedule", time.monotonic() - core_t0)

        if result.pod_bind_info is not None:
            # Free resource appeared; the pod will bind via the filter
            # path (the algorithm does NOT assume-bind in this phase).
            common.log.info(
                "[%s]: Pod is waiting for filterRoutine as free resource "
                "appeared",
                pod.key,
            )
            if rec is not None:
                rec.verdict = "free-resource"
                rec.note("free resource appeared; pod will bind via filter")
            return ei.ExtenderPreemptionResult()

        if result.pod_preempt_info is not None:
            if rec is not None:
                rec.verdict_preempt(result.pod_preempt_info.victim_pods)
            self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
                pod=pod,
                pod_state=PodState.PREEMPTING,
                pod_schedule_result=result,
            )
            nodes_victims: Dict[str, ei.MetaVictims] = {}
            for victim in result.pod_preempt_info.victim_pods:
                node = victim.node_name
                nodes_victims.setdefault(node, ei.MetaVictims()).pods.append(
                    ei.MetaPod(uid=victim.uid)
                )
            common.log.info(
                "[%s]: Pod is preempting victims on nodes %s",
                pod.key,
                sorted(nodes_victims),
            )
            return ei.ExtenderPreemptionResult(
                node_name_to_meta_victims=nodes_victims
            )

        self.pod_schedule_statuses[pod.uid] = PodScheduleStatus(
            pod=pod, pod_state=PodState.WAITING, pod_schedule_result=result
        )
        wait_reason = "Pod is waiting for preemptible or free resource to appear"
        if result.pod_wait_info is not None and result.pod_wait_info.reason:
            wait_reason += ": " + result.pod_wait_info.reason
        common.log.info("[%s]: %s", pod.key, wait_reason)
        if rec is not None:
            rec.verdict_wait(wait_reason)
        return ei.ExtenderPreemptionResult()

    # ------------------------------------------------------------------ #
    # Inspect delegates (reference: scheduler.go:723-745)
    # ------------------------------------------------------------------ #

    def get_all_affinity_groups(self) -> Dict:
        with self._lock:
            return self.core.get_all_affinity_groups()

    def get_affinity_group(self, name: str) -> Dict:
        with self._lock:
            return self.core.get_affinity_group(name)

    def get_cluster_status(self) -> Dict:
        return {
            "physicalCluster": self.get_physical_cluster_status(),
            "virtualClusters": self.get_all_virtual_clusters_status(),
        }

    def get_physical_cluster_status(self) -> List[Dict]:
        """Mirrored per-chain statuses OFF the global lock order: an
        epoch-clean chain serves its cached mirror without any lock at
        all, and a dirty chain rebuilds under only ITS chain section — a
        scrape loop can no longer stall filters on other chains (the
        pre-observability behavior took the whole-cluster global mode
        per scrape). A chain mutating between its epoch check and the
        mirror read serves the previous complete mirror — the same
        point-in-time semantics any scrape of a live scheduler has."""
        out: List[Dict] = []
        core = self.core
        ot_vc_map = None
        for chain in core.full_cell_list:
            cached = core._phys_status_cache.get(chain)
            if cached is not None and cached[0] == core.chain_epoch(chain):
                out.extend(cached[1])
            else:
                with self._locks.section((chain,)):
                    if ot_vc_map is None:
                        # One OT-cell walk shared by every dirty chain of
                        # this scrape (built inside the first section).
                        ot_vc_map = core._ot_cell_vc_by_address()
                    out.extend(core.physical_chain_status(chain, ot_vc_map))
        return out

    def get_all_virtual_clusters_status(self) -> Dict[str, List[Dict]]:
        return {
            vc: self.get_virtual_cluster_status(vc)
            for vc in self.core.vc_schedulers
        }

    def get_virtual_cluster_status(self, vcn: str) -> List[Dict]:
        """Epoch-keyed VC status mirror: clean reads are lock-free, a
        dirty rebuild locks only the VC's own chains (its virtual trees
        live there; the opportunistic cells' shallow statuses are
        single-attribute reads, safe without their chains' locks)."""
        core = self.core
        cached = core._vc_status_cache.get(vcn)
        if cached is not None and cached[0] == core.epoch_total():
            return cached[1]
        vcs = core.vc_schedulers.get(vcn)
        if vcs is None:
            # Unknown VC: let the core raise its user error.
            return core.get_virtual_cluster_status(vcn)
        chains = set(vcs.non_pinned_preassigned)
        for ccl in vcs.pinned_cells.values():
            chains.add(ccl[ccl.top_level][0].chain)
        with self._locks.section(chains):
            return core.get_virtual_cluster_status(vcn)

    def get_metrics(self) -> Dict:
        """Metrics snapshot WITHOUT entering the chain-lock order (the
        lock-free exposition path, doc/observability.md): every value is
        either guarded by a private micro-lock (SchedulerMetrics, the
        histograms, PhaseStats), an atomic-under-the-GIL container read
        (set/dict lengths, deque/dict copies), or a gauge refreshed under
        the lock at its mutation site (_refresh_stranded_locked). A
        Prometheus scrape loop therefore NEVER stalls filter throughput,
        and a wedged filter never blocks the scrape that would tell you."""
        snap = self.metrics.snapshot()
        # Merge the core-side phase accumulators (leaf-cell search happens
        # inside the topology-aware schedulers; see placement.PhaseStats).
        snap["phases"].update(self.core.phase_stats.snapshot())
        # Concurrent-core counters (doc/hot-path.md): per-chain lock-wait
        # breakdown (locks.GLOBAL_KEY aggregates the global-guard holders),
        # decode-free gang admissions, and preempt probes served from the
        # epoch-gated victims cache.
        snap["lockSharding"] = (
            "global" if self._locks.force_global else "chains"
        )
        snap["lockWaitByChain"] = self._locks.wait_snapshot()
        core = self.core
        snap["gangAdmissionBatchedCount"] = core.gang_admission_batched_count
        snap["preemptProbeIncrementalCount"] = (
            core.preempt_probe_incremental_count
        )
        snap["traceSampledCount"] = self.tracer.sampled_count
        snap["mappingRetryCount"] = core.mapping_retry_count
        # HA / snapshot recovery plane: counts from the LAST recovery
        # (gauges — a restart resets them by definition), the recovery
        # mode flag, and the leadership gauge.
        snap["snapshotImportedPodCount"] = self._snapshot_imported_count
        snap["snapshotDeltaPodCount"] = self._snapshot_delta_count
        # Seconds since the last snapshot landed (-1 until the first
        # flush): the starvation gauge behind the max-staleness override.
        last_flush = self._last_flush_monotonic
        snap["snapshotAgeSeconds"] = (
            -1.0
            if last_flush is None
            else round(time.monotonic() - last_flush, 3)
        )
        snap["recoveryMode"] = self._recovery_mode
        snap["leader"] = self.is_leader()
        snap["quarantinedPodCount"] = len(self.quarantined_pods)
        # set(dict) and list(dict.values()) are single-opcode C-level
        # copies — atomic under the GIL even against concurrent mutators.
        snap["strandedGroupCount"] = len(
            self._stranded_names & set(core.affinity_groups)
        )
        snap["badNodeCount"] = len(core.bad_nodes)
        snap["badChipCount"] = sum(
            len(c) for c in list(core.bad_chips.values())
        )
        snap["drainingChipCount"] = sum(
            len(c) for c in list(core.draining_chips.values())
        )
        snap["healthPendingCount"] = self._damper.pending_count()
        snap["ready"] = self.is_ready()
        # Boot-phase breakdown (doc/observability.md): wall seconds per
        # boot phase — compile / healthInit / nodeAdd / fingerprint /
        # recovery — so a standby cold-start is observable, not inferred.
        snap["bootPhaseSeconds"] = {
            k: round(v, 6) for k, v in core.boot_phase_seconds.items()
        }
        # Shadow what-if plane (doc/observability.md): forecast counters
        # and fork staleness. The keys are always present (golden metrics
        # schema); zeros/-1 until the plane's lazy construction.
        plane = self._whatif
        snap.update(
            plane.metrics_snapshot()
            if plane is not None
            else dict(WHATIF_EMPTY_METRICS)
        )
        # Black-box plane (doc/observability.md): live-audit runs and
        # violations, flight-recorder volume. Keys always present
        # (golden metrics schema); zeros while disabled.
        snap.update(dict(BLACKBOX_EMPTY_METRICS))
        aud = self.live_auditor
        if aud is not None:
            snap.update(aud.metrics_snapshot())
        recd = self.recorder
        if recd is not None:
            snap.update(recd.metrics_snapshot())
        # Durable-state plane v2 (doc/observability.md): integrity-scrub
        # runs, divergences, and repairs. Keys always present; zeros
        # while the scrubber is disabled.
        snap.update(dict(SCRUB_EMPTY_METRICS))
        scrub = self.scrubber
        if scrub is not None:
            snap.update(scrub.metrics_snapshot())
        # One wire (scheduler.wire): per-codec transport bytes and
        # delta-suggested-set resyncs are TRANSPORT-plane counters — the
        # single-process core has no internal transport, so the keys are
        # schema-stable zeros here; the sharded frontend
        # (shards.ShardedScheduler.get_metrics) overlays the real values.
        snap["wireBytesTotal"] = {"binary": 0, "pickle": 0, "json": 0}
        snap["deltaSuggestedResyncCount"] = 0
        # Shard supervision plane (scheduler.supervisor): same pattern —
        # a single process has no shard workers to supervise, so the
        # counters are schema-stable zeros here and the sharded frontend
        # overlays the live values (plus the per-shard shardUp gauge).
        snap["shardRestartCount"] = 0
        snap["shardDegradedWaitCount"] = 0
        # shardDown fast waits are served by the sharded frontend's
        # lock-free certificate cache; schema-stable zero here.
        snap["shardDownFastWaitCount"] = 0
        # Control-plane weather plane (doc/fault-model.md): the vane's
        # numeric state (0 clear / 1 brownout / 2 blackout) + monotone
        # epoch, and the intent journal's accounting (invariant:
        # journaled == drained + superseded + dropped + discarded +
        # depth).
        snap["apiserverWeather"] = self.weather_vane.state()
        snap["apiserverWeatherEpoch"] = self.weather_vane.epoch
        jc = self.intent_journal.counters()
        snap["intentJournalDepth"] = jc["depth"]
        snap["intentJournaledCount"] = jc["journaled"]
        snap["intentSupersededCount"] = jc["superseded"]
        snap["intentCoalescedCount"] = jc["coalesced"]
        snap["intentDrainedCount"] = jc["drained"]
        snap["intentDroppedCount"] = jc["dropped"]
        snap["intentDiscardedCount"] = jc["discarded"]
        # hived_build_info labels (rendered as a constant-1 gauge): the
        # deploy-identity facts an operator cross-checks first in any
        # incident — snapshot schema, config fingerprint prefix, shard
        # count, and the hatch states that change scheduling behavior.
        snap["buildInfo"] = {
            "snapshotSchema": str(snapshot_mod.SCHEMA_VERSION),
            "configFingerprint": (self._config_fingerprint or "")[:12],
            "shards": "0",
            "lazyVc": (
                "on"
                if os.environ.get("HIVED_LAZY_VC", "1").strip() != "0"
                else "off"
            ),
            "waitCache": "on" if self.wait_cache_enabled else "off",
            "nodeEventFastpath": (
                "on" if self.node_event_fastpath else "off"
            ),
            "liveAudit": "on" if aud is not None else "off",
            "flightRecorder": "on" if recd is not None else "off",
        }
        return snap

    def is_leader(self) -> bool:
        """True when this process may write to the cluster: either HA is
        disabled (no elector installed — single-scheduler deployments,
        tests, simulators) or the installed elector currently holds an
        unexpired leader lease."""
        lead = self.leadership
        return lead is None or lead.is_leader()

    def get_ha(self) -> Dict:
        """Inspect payload for /v1/inspect/ha: leadership, the last
        recovery's mode and delta counts, and snapshot persistence state."""
        lead = self.leadership
        m = self.metrics.snapshot()
        payload: Dict = {
            "haEnabled": lead is not None,
            "leader": self.is_leader(),
            "ready": self.is_ready(),
            "recoveryMode": self._recovery_mode,
            "snapshot": {
                "watermark": self._watermark,
                "persistCount": m["snapshotPersistCount"],
                "persistFailureCount": m["snapshotPersistFailureCount"],
                "fallbackCount": m["snapshotFallbackCount"],
                "importedPodCount": self._snapshot_imported_count,
                "deltaPodCount": self._snapshot_delta_count,
                "flusherRunning": self._flusher_thread is not None,
            },
            # Control-plane weather plane: the vane's classification and
            # the intent journal's live accounting.
            "weather": self.weather_vane.snapshot(),
            "intentJournal": self.intent_journal.counters(),
        }
        if lead is not None:
            payload["identity"] = getattr(lead, "identity", "")
            payload["observedHolder"] = getattr(lead, "observed_holder", "")
            payload["leaseTransitions"] = getattr(
                lead, "transition_count", 0
            )
            # Lease weather semantics (scheduler.ha): cannot-renew
            # (apiserver unreachable) vs superseded (another holder), and
            # warm own-lease resumptions that skipped cold takeover.
            payload["leaseWeather"] = getattr(lead, "lease_weather", "ok")
            payload["cannotRenewCount"] = getattr(
                lead, "cannot_renew_count", 0
            )
            payload["supersededCount"] = getattr(
                lead, "superseded_count", 0
            )
            payload["ownReacquireCount"] = getattr(
                lead, "own_reacquire_count", 0
            )
        return payload

    def whatif_routine(self, payload: Dict) -> Dict:
        """POST /v1/inspect/whatif — the shadow what-if plane
        (scheduler.whatif, doc/user-manual.md "When will my pod
        schedule?"): snapshot-forked admission forecasts with promised
        ETAs. The plane is constructed lazily on first use; its
        construction arms the read-only-fork audit on this scheduler."""
        return self.whatif.serve(payload)

    @property
    def whatif(self):
        """The lazily-constructed what-if plane (benches and the sim
        driver reach it directly; HTTP goes through whatif_routine).
        Double-checked under _whatif_init_lock: exactly one plane per
        scheduler, ever."""
        plane = self._whatif
        if plane is None:
            from . import whatif as whatif_mod

            with self._whatif_init_lock:
                plane = self._whatif
                if plane is None:
                    plane = self._whatif = whatif_mod.WhatIfPlane(self)
        return plane

    def get_decisions(
        self,
        n: Optional[int] = None,
        verdict: Optional[str] = None,
        gate: Optional[str] = None,
    ) -> Dict:
        """Inspect payload for /v1/inspect/decisions: the latest-N ring.
        ``verdict`` / ``gate`` slice the journal server-side
        (?verdict=wait&gate=vcQuota — doc/observability.md) so operators
        can ask "every WAIT blocked on quota" without dumping the ring;
        filters apply BEFORE the latest-N cut, so ?n= bounds the matches,
        not the scan window."""
        if verdict is None and gate is None:
            return {"items": self.decisions.snapshot(n)}
        items = [
            d
            for d in self.decisions.snapshot()
            if _decision_matches(d, verdict, gate)
        ]
        if n is not None and n >= 0:
            items = items[-n:] if n > 0 else []
        return {"items": items}

    def get_flightrecorder(self, full: bool = False) -> Dict:
        """Inspect payload for /v1/inspect/flightrecorder: the window
        summary, or (?full=1) the whole dumpable recording — the unit
        `python -m hivedscheduler_tpu.sim --replay-recording` consumes."""
        rec = self.recorder
        if rec is None:
            return {"enabled": False}
        payload = rec.recording() if full else rec.summary()
        payload["enabled"] = True
        return payload

    def get_decision(self, key: str) -> Dict:
        """Per-pod lookup (uid or namespace/name) of the latest decision."""
        rec = self.decisions.lookup(key)
        if rec is None:
            raise api.not_found(
                f"No decision recorded for pod {key} (journal keeps the "
                f"last {self.decisions.capacity} decisions)"
            )
        return rec

    def get_traces(self, n: Optional[int] = None) -> Dict:
        """Inspect payload for /v1/inspect/traces: the sampled-span ring."""
        return {
            "sample": self.tracer.sample,
            "items": self.tracer.snapshot(n),
        }


def _decision_matches(
    d: Dict, verdict: Optional[str], gate: Optional[str]
) -> bool:
    """The ?verdict= / ?gate= journal slice: verdict is an exact match;
    gate matches any per-chain rejection's gate OR a WAIT certificate's
    blocking gate."""
    if verdict is not None and d.get("verdict") != verdict:
        return False
    if gate is not None:
        in_rejections = any(
            a.get("gate") == gate for a in d.get("rejections") or []
        )
        cert = d.get("certificate") or {}
        if not in_rejections and cert.get("gate") != gate:
            return False
    return True
