"""The shard supervision plane: crash/hang detection, hot resurrection,
degraded-mode bookkeeping.

PR 8's multi-process frontend inherited HiveD's single-binary blind spot:
a shard worker that dies (or wedges) marks its backend dead forever —
one SIGKILL takes the shard's chain families offline until a full
restart, despite the partitioned recovery machinery (PR 7's per-shard
snapshot slots + annotation delta replay) being exactly what a per-shard
resurrection needs. This module closes the loop:

- **Liveness** — the backends themselves detect death (pipe EOF) and
  hangs (per-verb deadlines, ``HIVED_SHARD_VERB_DEADLINE_S``); the
  supervisor's heartbeat additionally catches a worker that died *idle*
  (nobody reading the pipe) via ``Process.is_alive``. Every failure is
  journaled as a ``_shard`` decision record carrying the exitcode /
  signal / in-flight verb the backend captured.

- **Hot resurrection** — respawn the worker through the frontend's own
  backend factory, then drive the shard's recovery through the existing
  PR-7 validation ladder against its own ``_PartitionStore`` slot (the
  worker loads + validates its snapshot partition, falls back to
  annotation delta replay of only its owned chains) fed from this
  module's **mirror journal** of idempotent informer state: the
  last-applied node set, the live pod set, and the health-clock tick
  count since boot/recovery. All other shards keep serving throughout.
  Restart storms are bounded by exponential backoff and a circuit
  breaker that degrades the shard to ``down`` after N consecutive
  failed resurrections.

- **Degraded mode** — while a shard is not ``up``, the frontend answers
  its routed filters with WAIT + a ``shardDown`` rejection certificate
  (epoch-stamped, so a cached certificate is invalidated by the
  resurrection's epoch bump), refuses its binds retriably (503), and
  skips it in inspect/metrics aggregation with explicit attribution.
  The counters here feed ``hived_shard_up{shard}`` /
  ``hived_shard_restarts_total`` / ``hived_shard_degraded_waits_total``.

The mirror journal is bounded by construction: nodes and pods are maps
keyed by name/uid holding only the LATEST state (cluster-sized, not
history-sized), and the tick count is one integer whose replay is capped
at :data:`TICK_REPLAY_CAP` (past the health damper horizon, additional
ticks only advance the clock). Why a mirror and not the kube informer:
resurrection must not depend on an apiserver round-trip being possible
at that moment — the inputs that built the live shards are replayed
from memory, and the chaos differential (tests/chaos.py supervise mode)
proves the mirror-recovered shard converges to the same chain-scoped
fingerprint + probe outcomes as a never-crashed twin recovered from the
harness's cluster truth.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from .. import common
from .types import Node, Pod

# Resurrection replays at most this many health ticks (1 RPC, worker-side
# loop). Past the damper horizon extra ticks are clock advancement only;
# the cap keeps the journal's replay cost bounded on long-lived parents.
TICK_REPLAY_CAP = 100_000

STATUS_UP = "up"
STATUS_RESURRECTING = "resurrecting"
STATUS_DOWN = "down"


class ShardJournal:
    """Bounded mirror of the idempotent informer-state verbs, replayed
    into a resurrected worker. Mutated only under the supervisor's lock
    (the frontend verbs call through the supervisor's note_* hooks)."""

    def __init__(self) -> None:
        self.nodes: Dict[str, Node] = {}
        self.pods: Dict[str, Pod] = {}
        self.ticks = 0

    def note_node(self, node: Node) -> None:
        self.nodes[node.name] = node

    def note_node_delete(self, name: str) -> None:
        self.nodes.pop(name, None)

    def note_pod(self, pod: Pod) -> None:
        self.pods[pod.uid] = pod

    def note_pod_delete(self, uid: str) -> None:
        self.pods.pop(uid, None)

    def note_tick(self) -> None:
        self.ticks += 1

    def reset(self, nodes, pods) -> None:
        """A full recovery re-anchors the mirror on its authoritative
        inputs (and zeroes the tick clock, like the recovery itself)."""
        self.nodes = {n.name: n for n in nodes}
        self.pods = {p.uid: p for p in pods}
        self.ticks = 0


class _ShardState:
    __slots__ = (
        "sid", "status", "restarts", "failures", "epoch", "last_exit",
        "next_attempt_at", "degraded_waits",
    )

    def __init__(self, sid: int):
        self.sid = sid
        self.status = STATUS_UP
        self.restarts = 0          # successful resurrections
        self.failures = 0          # CONSECUTIVE failed resurrections
        self.epoch = 0             # bumps on every resurrection
        self.last_exit: Optional[Dict] = None
        self.next_attempt_at = 0.0
        self.degraded_waits = 0

    def to_dict(self) -> Dict:
        return {
            "shard": self.sid,
            "status": self.status,
            "restarts": self.restarts,
            "consecutiveFailures": self.failures,
            "epoch": self.epoch,
            "degradedWaits": self.degraded_waits,
            "lastExit": self.last_exit,
        }


class ShardSupervisor:
    """Per-shard liveness + resurrection driver for a ShardedScheduler
    frontend. ``check_now()`` is the deterministic entry point (tests,
    chaos); ``start()`` runs it on a heartbeat thread in production."""

    def __init__(self, front, clock=time.monotonic):
        self.front = front
        cfg = front.config
        self.max_failures = int(
            getattr(cfg, "shard_max_resurrection_failures", 3)
        )
        self.backoff_base_s = float(
            getattr(cfg, "shard_resurrection_backoff_seconds", 1.0)
        )
        self.backoff_cap_s = float(
            getattr(cfg, "shard_resurrection_backoff_cap_seconds", 30.0)
        )
        self.clock = clock
        self.journal = ShardJournal()
        # RLock: the frontend's degraded-wait path runs under the
        # supervisor lock and journals through front.decisions, whose
        # commit path never re-enters here — but resurrection calls
        # frontend verbs that call back into note_* hooks.
        self._lock = threading.RLock()
        self.states = [
            _ShardState(sid) for sid in range(len(front.shards))
        ]
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    # -- status reads (hot path: one dict lookup, no backend call) ----- #

    def is_up(self, sid: int) -> bool:
        return self.states[sid].status == STATUS_UP

    def status(self, sid: int) -> str:
        return self.states[sid].status

    def epoch(self, sid: int) -> int:
        return self.states[sid].epoch

    def down_shards(self) -> List[int]:
        return [
            s.sid for s in self.states if s.status != STATUS_UP
        ]

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return [s.to_dict() for s in self.states]

    # -- journal feeding (called by the frontend's informer verbs) ----- #

    def note_node(self, node: Node) -> None:
        with self._lock:
            self.journal.note_node(node)

    def note_node_delete(self, name: str) -> None:
        with self._lock:
            self.journal.note_node_delete(name)

    def note_pod(self, pod: Pod) -> None:
        with self._lock:
            self.journal.note_pod(pod)

    def note_pod_delete(self, uid: str) -> None:
        with self._lock:
            self.journal.note_pod_delete(uid)

    def note_tick(self) -> None:
        with self._lock:
            self.journal.note_tick()

    def note_recovered(self, nodes, pods) -> None:
        with self._lock:
            self.journal.reset(nodes, pods)

    # -- failure intake ------------------------------------------------ #

    def note_failure(self, sid: int, err: Optional[BaseException] = None,
                     method: str = "") -> None:
        """A ShardWorkerError surfaced (or the heartbeat found a dead
        worker): transition the shard out of ``up`` exactly once and
        journal the forensic record. Idempotent — every caller racing
        the same death funnels here."""
        with self._lock:
            st = self.states[sid]
            if st.status != STATUS_UP:
                return
            st.status = STATUS_RESURRECTING
            st.failures = 0
            st.next_attempt_at = self.clock()  # first attempt: immediate
            backend = self.front.shards[sid]
            exit_info = dict(getattr(backend, "last_exit", None) or {})
            if not exit_info and err is not None:
                exit_info = {
                    "cause": getattr(err, "cause", "died"),
                    "exitcode": getattr(err, "exitcode", None),
                    "signal": getattr(err, "signal_name", ""),
                    "method": getattr(err, "method", method),
                }
            st.last_exit = exit_info or None
            self._journal_record(
                sid,
                "shard-failed",
                "shard %d worker %s (exitcode=%s signal=%s method=%s)" % (
                    sid,
                    exit_info.get("cause", "died"),
                    exit_info.get("exitcode"),
                    exit_info.get("signal") or "-",
                    exit_info.get("method") or "-",
                ),
            )
            common.log.error(
                "shard %d worker failed (%s); supervision engaged",
                sid, exit_info.get("cause", "died"),
            )

    def note_degraded_wait(self, sid: int) -> None:
        with self._lock:
            self.states[sid].degraded_waits += 1

    # -- liveness + resurrection driver -------------------------------- #

    def check_now(self, resurrect: bool = True) -> Dict:
        """One supervision pass: detect silently-dead workers, attempt
        due resurrections. Deterministic (no sleeping) — the heartbeat
        thread and the tests both drive exactly this."""
        detected, resurrected, still_down = [], [], []
        for st in self.states:
            sid = st.sid
            if st.status == STATUS_UP:
                backend = self.front.shards[sid]
                alive = True
                try:
                    alive = backend.is_alive()
                except Exception:  # noqa: BLE001
                    alive = False
                if not alive:
                    self.note_failure(sid)
                    detected.append(sid)
        if resurrect:
            for st in self.states:
                if st.status != STATUS_RESURRECTING:
                    if st.status == STATUS_DOWN:
                        still_down.append(st.sid)
                    continue
                if self.clock() < st.next_attempt_at:
                    continue
                if self._attempt(st.sid):
                    resurrected.append(st.sid)
                elif self.states[st.sid].status == STATUS_DOWN:
                    still_down.append(st.sid)
        return {
            "detected": detected,
            "resurrected": resurrected,
            "down": still_down,
        }

    def _attempt(self, sid: int) -> bool:
        with self._lock:
            st = self.states[sid]
            try:
                self._resurrect(sid)
            except Exception as e:  # noqa: BLE001
                st.failures += 1
                delay = min(
                    self.backoff_cap_s,
                    self.backoff_base_s * (2 ** (st.failures - 1)),
                )
                st.next_attempt_at = self.clock() + delay
                if st.failures >= self.max_failures:
                    st.status = STATUS_DOWN
                    self._journal_record(
                        sid,
                        "shard-down",
                        f"shard {sid} circuit breaker open after "
                        f"{st.failures} failed resurrections: {e}",
                    )
                    common.log.error(
                        "shard %d degraded to down after %d failed "
                        "resurrections: %s", sid, st.failures, e,
                    )
                else:
                    self._journal_record(
                        sid,
                        "shard-retry",
                        f"shard {sid} resurrection failed "
                        f"({st.failures}/{self.max_failures}), backoff "
                        f"{delay:.1f}s: {e}",
                    )
                    common.log.warning(
                        "shard %d resurrection failed (%d/%d): %s",
                        sid, st.failures, self.max_failures, e,
                    )
                return False
            st.status = STATUS_UP
            st.restarts += 1
            st.failures = 0
            st.epoch += 1
            self._journal_record(
                sid,
                "shard-resurrected",
                f"shard {sid} resurrected (epoch {st.epoch}, "
                f"restart {st.restarts})",
            )
            common.log.warning(
                "shard %d resurrected (epoch %d)", sid, st.epoch
            )
            return True

    def _resurrect(self, sid: int) -> None:
        """Respawn + per-shard recovery. Any exception leaves the old
        (dead) backend in place for the next attempt — the frontend's
        degraded-mode path keeps answering for the shard meanwhile."""
        front = self.front
        old = front.shards[sid]
        # Slice the mirror exactly the way recover() slices the cluster:
        # nodes by chain targets, pods by recovery routing (an unroutable
        # pod belongs to every slice, so it belongs to this one).
        nodes = [
            n for n in self.journal.nodes.values()
            if sid in front._node_targets(n.name)
        ]
        pods = [
            p for p in self.journal.pods.values()
            if front._route_recovery_pod(p) in (sid, None)
        ]
        ticks = min(self.journal.ticks, TICK_REPLAY_CAP)
        if self.journal.ticks > TICK_REPLAY_CAP:
            common.log.warning(
                "shard %d tick replay clamped: %d -> %d",
                sid, self.journal.ticks, TICK_REPLAY_CAP,
            )
        try:
            old.close()
        except Exception:  # noqa: BLE001 — already-dead close must not
            pass           # block the respawn
        backend = front._spawn_backend(sid, old.owned_chains)
        try:
            self._recover_shard(backend, sid, nodes, pods, ticks)
        except BaseException:
            try:
                backend.close()
            except Exception:  # noqa: BLE001
                pass
            raise
        # Swap in, then reset the frontend's per-shard transport memos
        # (suggested-set sends, delta bases) and rebuild the routing maps
        # for THIS shard from its recovered state.
        front.shards[sid] = backend
        state = backend.call("list_state")
        with front._maps_lock:
            front._nodes_sent[sid] = set()
            front._nodes_acked[sid] = None
            for uid in [
                u for u, s in front._uid_shard.items() if s == sid
            ]:
                del front._uid_shard[uid]
            for g in [
                g for g, s in front._group_shard.items() if s == sid
            ]:
                del front._group_shard[g]
            for uid in state["uids"]:
                front._uid_shard[uid] = sid
            for g in state["groups"]:
                front._group_shard[g] = sid
        # Post-resurrection flight-recorder windows must re-anchor on a
        # fresh snapshot: the pre-crash anchor no longer matches the
        # resurrected shard's projection lineage.
        rec = front.recorder
        if rec is not None:
            rec.force_reanchor()

    def _recover_shard(self, backend, sid: int, nodes, pods,
                       ticks: int) -> None:
        """Drive one respawned worker through the PR-7 recovery ladder
        (snapshot slot validation, annotation delta replay of its owned
        chains) and replay the mirror's idempotent clock. The chaos
        sensitivity meta-test no-ops THIS seam to prove the supervise
        differential has teeth."""
        backend.call("recover_slice", nodes, pods, None)
        if ticks:
            backend.call("replay_health_ticks", ticks)
        if self.front.is_ready():
            backend.call("mark_ready")

    def ensure_all_up(self) -> None:
        """Force-respawn every non-up shard, resetting breakers — the
        full-recovery path (frontend recover()) is about to replay
        authoritative state into every backend, so per-shard recovery
        and backoff bookkeeping are both moot."""
        with self._lock:
            for st in self.states:
                sid = st.sid
                backend = self.front.shards[sid]
                dead = st.status != STATUS_UP
                try:
                    dead = dead or not backend.is_alive()
                except Exception:  # noqa: BLE001
                    dead = True
                if dead:
                    try:
                        backend.close()
                    except Exception:  # noqa: BLE001
                        pass
                    self.front.shards[sid] = self.front._spawn_backend(
                        sid, backend.owned_chains
                    )
                    if st.status != STATUS_UP:
                        st.epoch += 1
                        st.restarts += 1
                st.status = STATUS_UP
                st.failures = 0
                st.next_attempt_at = 0.0

    # -- heartbeat thread (production) --------------------------------- #

    def start(self, interval_s: Optional[float] = None) -> bool:
        interval = (
            getattr(
                self.front.config,
                "shard_supervision_interval_seconds", 5.0,
            )
            if interval_s is None
            else interval_s
        )
        if interval <= 0 or self._thread is not None:
            return False
        stop = threading.Event()

        def loop() -> None:
            while not stop.wait(interval):
                try:
                    self.check_now()
                except Exception:  # noqa: BLE001
                    common.log.exception("shard supervision pass failed")

        t = threading.Thread(
            target=loop, name="hived-shard-supervisor", daemon=True
        )
        self._stop, self._thread = stop, t
        t.start()
        return True

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self._stop = self._thread = None

    # -- journaling ---------------------------------------------------- #

    def _journal_record(self, sid: int, verdict: str,
                        detail: str) -> None:
        """A `_shard` record in the FRONTEND decision journal (the
        audit-plane `_audit` pattern): supervision lifecycle is part of
        the explainability surface — `/v1/inspect/decisions` shows WHY
        a family's pods started waiting."""
        try:
            journal = self.front.decisions
            rec = journal.begin("_shard", f"_shard-{sid}", "supervise")
            rec.verdict_error(detail)
            rec.verdict = verdict
            journal.commit(rec)
        except Exception:  # noqa: BLE001 — diagnostics must never raise
            common.log.exception("shard supervision journaling failed")
