"""Multi-process scheduling core: per-chain-family worker shards.

PR 5 sharded the scheduler lock per cell chain and proved the scheduling
state is partitioned by chain (doc/hot-path.md "The lock-sharding
contract") — but CPython's GIL still serializes the pure-Python schedule
math, so the concurrent win came only from de-serializing blocking paths.
This module removes that ceiling: the core is sharded by CHAIN FAMILY
into worker processes, so filter compute scales with cores.

Architecture (doc/hot-path.md "The multi-process contract"):

- **Partition.** Chains are grouped into *families*: the connected
  components of the "shares a leaf SKU" relation (a pod naming leaf type
  T may probe every chain carrying T, so those chains must co-reside).
  Families are dealt round-robin (in sorted order) onto N shards; each
  shard owns a disjoint chain set — exactly the per-chain partition
  ``locks.py`` proves disjoint, coarsened to routable units.
- **Workers.** Each shard is a full :class:`HivedScheduler` over the full
  compiled config, but it only ever *sees* traffic for its owned chains:
  pod verbs are routed by the pod's lock-chain derivation, and node
  events are delivered only to the shards whose chains host the node.
  Foreign chains therefore stay in the constructor's all-nodes-bad
  bootstrap state — zero usable capacity — so a shard can never place a
  pod on a chain it does not own. Per-chain state purity (the PR-5
  theorem: scheduling one chain reads only that chain's cell trees,
  quota ledgers, and doom counters) makes each shard's owned-chain state
  bit-identical to a single process's, which the cross-process
  differential suite asserts (tests/test_proc_shards.py).
- **Routing.** The parent derives the pod's reachable chains the same
  way ``HivedScheduler._pod_lock_chains`` does (leaf SKU -> chains,
  pinned cell -> chain, untyped guaranteed -> VC quota chains, bound
  node -> node's chains) and maps them to families. A single-family pod
  goes straight to the owning shard (the hot path — every typed or
  pinned pod). A pod whose chains span families (only possible for
  untyped pods) degrades to the *sweep*: the filter runs as a
  LEAF-TYPE-GRANULAR scan — the global sorted leaf-type order, chunked
  into maximal consecutive same-shard runs, each chunk probed on its
  owning shard with the scan restricted to exactly its leaf types
  (``filter_routine(leaf_types=...)``) — so the probe order, and
  therefore the placement found, is byte-identical to the in-process
  any-leaf-type chain scan (the PR-8 shard-major deviation is retired;
  placement-found-iff holds chunk by chunk since the chunks partition
  the full scan). The rarely-swept preempt verb keeps the shard-major
  order (first non-empty victim set wins).
- **Global mode.** Operations spanning shards (multi-shard node/health
  events, clock ticks, recovery bracket work) run as a TWO-PHASE
  broadcast: phase 1 stages the operation on every target shard, phase 2
  commits in ascending shard order. No shard applies until every shard
  has staged, and the commit order is deterministic — the chaos
  sensitivity meta-test pins seeds that die when phase 2 is no-op'd.
  Reads (inspect, metrics) are plain gathers merged by the parent.
- **Partitioned durable state.** Each shard persists its own doomed
  ledger and snapshot projection; the parent stores them side by side
  (one envelope per ConfigMap family, keyed by shard and stamped with
  the partition fingerprint) and recovery FANS OUT: every shard
  restores and delta-replays its own chains, in parallel for process
  backends. A partition change (different shard count or chain
  ownership) invalidates the envelope and recovery falls back to the
  full annotation replay — the deterministic degraded mode.
- **Transports.** ``proc`` backends are real OS processes (true parallel
  filter compute; the bench stage measures the scaling curve); ``local``
  backends run the identical routing/broadcast/partition code paths
  in-process, giving the chaos harness deep-inspection access while
  hammering the exact protocol the process boundary uses.

``HIVED_PROC_SHARDS=0`` (the default) bypasses this module entirely:
``__main__`` serves the plain in-process sharded scheduler, byte-for-byte
today's path.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import pickle
import threading
import zlib
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from .. import common
from ..api import constants, extender as ei, types as api
from ..api.config import Config
from . import recorder as recorder_pkg
from . import wire as wire_mod
from .framework import (
    HivedScheduler,
    KubeClient,
    NullKubeClient,
    _decision_matches,
)
from .types import (
    Node,
    Pod,
    extract_pod_scheduling_spec,
    is_bound,
)

PROC_SHARDS_ENV = "HIVED_PROC_SHARDS"

# Shared-memory filter ring (doc/hot-path.md "Boot and transport
# plane"): the bulk payloads of the filter hot path ride a per-shard
# shared-memory ring instead of the pipe; the pipe keeps carrying the
# (tiny) control frames, ordering, and wakeups. "0" restores the
# pipe-payload path byte-for-byte; HIVED_SHARD_RING_BYTES sizes each
# direction's ring (default 4 MiB).
SHARD_RING_ENV = "HIVED_SHARD_RING"
SHARD_RING_BYTES_ENV = "HIVED_SHARD_RING_BYTES"
_RING_DEFAULT_BYTES = 4 << 20
# Payloads below this ride the pipe even with the ring enabled: the
# PR-8 filter_fast memo already keeps the steady-state per-RPC payload
# at pod-dict scale (~1-2 KB), where the ring's extra explicit pickle +
# copy measurably LOSES to the pipe's one kernel copy (the honest-null
# arithmetic in doc/hot-path.md "Boot and transport plane"). The ring
# earns its keep on the large frames — first-send suggested-node lists,
# oversized bodies/results — that otherwise stall the pipe at p99.
_RING_MIN_BYTES = 8 << 10
# Methods whose args/result ride the ring: the dominant per-RPC payloads
# (filter body / pod dict on the way in, the suggested-node-scale result
# on the way out). Everything else — control ops, node events, recovery
# — keeps the plain pipe.
_RING_METHODS = frozenset({"filter_routine_raw", "filter_fast"})
_RING_MARK = "__hivedRing__"


def _ring_candidate_args(method: str, args: tuple) -> bool:
    """O(1) pre-pickle size hint for REQUEST frames: steady-state small
    frames (the overwhelmingly common case under the filter_fast memo)
    must not pay a speculative pickle just to learn they are under the
    floor. filter_routine_raw's body length bounds its pickle within a
    few bytes; filter_fast is large only when the suggested-node list is
    actually being sent (args[2] is not None)."""
    if method == "filter_routine_raw":
        body = args[0] if args else b""
        return isinstance(body, (bytes, bytearray)) and (
            len(body) >= _RING_MIN_BYTES
        )
    if method == "filter_fast":
        return len(args) > 2 and args[2] is not None
    return True


# ------------------------------------------------------------------ #
# One wire (doc/hot-path.md "One wire"): every pipe/ring frame is
# packed by _pack_frame and sniffed open by _unpack_frame. With
# HIVED_WIRE on (the default) frames go out in scheduler/wire.py's
# binary format, falling back to pickle PER FRAME when a payload is
# not wire-expressible (both codecs' first bytes are disjoint, so the
# receive side never guesses); HIVED_WIRE=0 is the legacy hatch —
# every frame goes out as pickle, which over send_bytes/recv_bytes is
# byte-identical to the Connection.send/recv the pre-wire code used.
# ------------------------------------------------------------------ #


def _wire_enabled() -> bool:
    return wire_mod.enabled()


def _pack_frame(obj, wire_on: bool) -> Tuple[bytes, str]:
    """Encode one pipe/ring frame; returns (bytes, codec name)."""
    if wire_on:
        try:
            return wire_mod.dumps(obj), "binary"
        except wire_mod.WireEncodeError:
            pass
    return pickle.dumps(obj), "pickle"


def _unpack_frame(buf):
    """Sniff + decode one frame. A WireVersionError propagates — both
    pipe ends run the same build, so a version mismatch here is
    corruption, not negotiation (the HTTP extender path is where a
    version mismatch falls back losslessly; see webserver/server.py)."""
    if wire_mod.is_wire(buf):
        return wire_mod.loads(buf)
    return pickle.loads(buf)


# Delta-encoded suggested sets: when the suggested-node list churns,
# the frontend ships (set id, base set id, removes, adds, crc, len)
# against a set the worker already caches instead of the full O(fleet)
# list. The set id IS the PR-12 suggested-set token (len, hash) — one
# memo (the frontend's _nodes_ids map) serves both the transport and
# the wait cache. The crc (zlib.crc32 — stable across processes,
# unlike hash()) plus the length make a corrupted or stale base a
# mechanical resync (__needNodes -> full list), never a wrong filter.
_DELTA_MARK = "__hivedDelta__"
# A delta only pays while it is small; past a quarter of the new list
# the full STRLIST send is both simpler and about as cheap.
_DELTA_MAX_FRACTION = 4


def _suggested_crc(names) -> int:
    return zlib.crc32("\x00".join(names).encode())


def _suggested_delta(base, new, base_id):
    """Exact edit script from tuple ``base`` to tuple ``new``: remove
    ``removes`` (base indices, ascending), then insert ``adds`` as
    (final index, name) in ascending order. Returns the wire marker
    tuple, or None when the script is too large or the surviving names
    were REORDERED (order matters — filter results may depend on it, so
    reorders resync with the full list rather than approximate)."""
    new_set = set(new)
    budget = len(new) // _DELTA_MAX_FRACTION + 1
    removes = []
    kept = []
    for i, b in enumerate(base):
        if b in new_set:
            kept.append(b)
        else:
            removes.append(i)
    if len(removes) > budget:
        return None
    adds = []
    j = 0
    kl = len(kept)
    for i, n in enumerate(new):
        if j < kl and kept[j] == n:
            j += 1
        else:
            adds.append((i, n))
            if len(adds) > budget:
                return None
    if j != kl:
        return None
    return (
        _DELTA_MARK, base_id, tuple(removes), tuple(adds),
        _suggested_crc(new), len(new),
    )


def _apply_suggested_delta(base, marker):
    """Worker-side delta apply + verify. Returns the rebuilt list, or
    None when the result fails the length/crc check (stale base,
    corrupted frame) — the caller answers __needNodes and the frontend
    resyncs with the full list."""
    _mark, _base_id, removes, adds, crc, length = marker
    if removes:
        rset = set(removes)
        out = [b for i, b in enumerate(base) if i not in rset]
    else:
        out = list(base)
    for i, n in adds:
        out.insert(i, n)
    if len(out) != length or _suggested_crc(out) != crc:
        return None
    return out


def _is_delta_marker(nodes) -> bool:
    return (
        type(nodes) is tuple
        and len(nodes) == 6
        and nodes[0] == _DELTA_MARK
    )


# Multiprocessing start method for proc backends. "spawn" is the default:
# the parent may carry JAX/XLA (or webserver) threads whose locks a fork
# would clone mid-flight; workers import only the scheduler layer, so the
# spawn cost is a one-time ~1s per worker.
PROC_START_ENV = "HIVED_PROC_START"

# Envelope key for the partitioned ledger/snapshot stores.
_ENVELOPE_KEY = "hivedShardPartition"


# --------------------------------------------------------------------- #
# Partition + routing
# --------------------------------------------------------------------- #


class RoutingTable:
    """The compile-time maps the parent routes by — plain data, built
    from the compiler's SPEC SCAN (compiler.physical_spec_metadata /
    chain_families), not from a throwaway compiled core: the routing
    facts are pure functions of the config, and at 50k hosts the old
    bootstrap compile (plus its all-nodes-bad init) was its own boot
    wall (doc/hot-path.md "Boot and transport plane").

    The family computation is the union of the per-leaf-SKU chain sets:
    two chains are in one family iff some leaf type reaches both. This is
    the finest partition under which every TYPED pod is single-family —
    the routable unit the per-chain lock partition coarsens to."""

    def __init__(self, config: Config):
        from ..algorithm import compiler

        pc = config.physical_cluster
        chains, node_chains, pinned_of_id = (
            compiler.physical_spec_metadata(config)
        )
        self.chains: Tuple[str, ...] = chains
        elements = compiler.build_cell_chains(pc.cell_types)
        leaf_chains: Dict[str, List[str]] = {}
        for chain in self.chains:
            leaf = elements[chain].leaf_cell_type
            leaf_chains.setdefault(str(leaf), []).append(chain)
        self.leaf_chains: Dict[str, Tuple[str, ...]] = {
            t: tuple(cs) for t, cs in leaf_chains.items()
        }
        self.quota_chains: Dict[str, Tuple[str, ...]] = {}
        self.pinned_chain: Dict[Tuple[str, str], str] = {}
        for vcn, spec in config.virtual_clusters.items():
            quota: List[str] = []
            for vcell in spec.virtual_cells:
                chain = vcell.cell_type.split(".")[0]
                if vcell.cell_number > 0 and chain not in quota:
                    quota.append(chain)
            self.quota_chains[str(vcn)] = tuple(quota)
            for pcell in spec.pinned_cells:
                pid = str(pcell.pinned_cell_id)
                if pid in pinned_of_id:
                    self.pinned_chain[(str(vcn), pid)] = pinned_of_id[pid]
        self.node_chains: Dict[str, Tuple[str, ...]] = dict(node_chains)
        self.families: Tuple[Tuple[str, ...], ...] = (
            compiler.chain_families(pc.cell_types, pc.physical_cells)
        )
        self.family_of_chain: Dict[str, int] = {
            c: i for i, fam in enumerate(self.families) for c in fam
        }

    def shard_plan(self, n_shards: int) -> List[Tuple[str, ...]]:
        """Owned-chain sets per shard: families dealt round-robin in
        sorted order. More shards than families leaves the tail shards
        empty (and they are simply not spawned)."""
        n = max(1, n_shards)
        buckets: List[List[str]] = [[] for _ in range(n)]
        for i, fam in enumerate(self.families):
            buckets[i % n].extend(fam)
        return [tuple(sorted(b)) for b in buckets if b]

    def pod_chains(
        self, pod: Pod, spec: Optional[api.PodSchedulingSpec]
    ) -> Optional[List[str]]:
        """Parent-side mirror of ``HivedScheduler._pod_lock_chains``
        (minus the live-group widening, which the frontend's group pin
        map supersedes). None = cannot be narrowed (undecodable spec or
        untyped opportunistic pod)."""
        if spec is None:
            return None
        chains: Optional[List[str]] = None
        if spec.pinned_cell_id:
            pinned = self.pinned_chain.get(
                (str(spec.virtual_cluster), str(spec.pinned_cell_id))
            )
            if pinned is None:
                return None  # unknown pinned cell: rejected inside
            chains = [pinned]
        elif spec.leaf_cell_type:
            typed = self.leaf_chains.get(spec.leaf_cell_type)
            if not typed:
                return None  # unknown SKU: rejected inside
            chains = list(typed)
        elif spec.priority >= constants.MIN_GUARANTEED_PRIORITY:
            quota = self.quota_chains.get(str(spec.virtual_cluster))
            if not quota:
                return None  # unknown VC / no quota: rejected inside
            chains = list(quota)
        else:
            return None  # untyped opportunistic: probes every chain
        if pod.node_name:
            for c in self.node_chains.get(pod.node_name, ()):
                if c not in chains:
                    chains.append(c)
        return chains

    def fingerprint(self, plan: List[Tuple[str, ...]]) -> str:
        """Stamps the partitioned ledger/snapshot envelopes: a different
        shard PLAN (count or chain ownership) must not deserialize
        another plan's partitions — each slot is one shard's whole-core
        projection and only its owned chains are authoritative."""
        return common.to_json({"plan": [list(p) for p in plan]})


# --------------------------------------------------------------------- #
# Shared-memory payload ring (proc transport)
# --------------------------------------------------------------------- #


class ShmRing:
    """Single-producer single-consumer byte ring over shared memory.

    Carries only PAYLOAD bytes; framing, ordering, and wakeup stay on
    the pipe: a control frame referencing a ring payload is sent AFTER
    the payload lands, and every consumer resolves ring frames in strict
    pipe-arrival order, so the head/tail counters are the only shared
    state (8-byte aligned little-endian slots; each update is a single
    memcpy under the GIL on either side). A payload that does not fit
    (ring full, or bigger than the ring) falls back to the pipe inline —
    per-frame, lossless, and invisible to the caller."""

    HDR = 16  # head u64 @0 (producer-owned), tail u64 @8 (consumer-owned)

    def __init__(self, name: Optional[str] = None,
                 size: int = _RING_DEFAULT_BYTES):
        from multiprocessing import shared_memory

        if name is None:
            self._shm = shared_memory.SharedMemory(
                create=True, size=self.HDR + size
            )
            self.owner = True
            self._shm.buf[: self.HDR] = b"\0" * self.HDR
        else:
            # Worker-side attach. The parent owns the segment lifecycle
            # (close() unlinks); spawned/forked workers share the
            # parent's resource-tracker process, so the attach-side
            # register is a set no-op and needs no counter-unregister —
            # an explicit unregister here would double-free against the
            # parent's unlink.
            self._shm = shared_memory.SharedMemory(name=name)
            self.owner = False
        self.size = self._shm.size - self.HDR
        self.name = self._shm.name

    def _counter(self, off: int) -> int:
        return int.from_bytes(bytes(self._shm.buf[off: off + 8]), "little")

    def _set_counter(self, off: int, v: int) -> None:
        self._shm.buf[off: off + 8] = v.to_bytes(8, "little")

    def try_write(self, payload: bytes) -> bool:
        """Producer side: append the payload if it fits, else False (the
        caller sends it inline on the pipe)."""
        n = len(payload)
        head = self._counter(0)
        tail = self._counter(8)
        if n > self.size - (head - tail):
            return False
        pos = head % self.size
        first = min(n, self.size - pos)
        buf = self._shm.buf
        buf[self.HDR + pos: self.HDR + pos + first] = payload[:first]
        if first < n:
            buf[self.HDR: self.HDR + (n - first)] = payload[first:]
        self._set_counter(0, head + n)
        return True

    def read(self, n: int) -> bytes:
        """Consumer side: pop exactly the next ``n`` bytes (ring frames
        are consumed in pipe order, so no offsets are needed)."""
        tail = self._counter(8)
        pos = tail % self.size
        first = min(n, self.size - pos)
        buf = self._shm.buf
        out = bytes(buf[self.HDR + pos: self.HDR + pos + first])
        if first < n:
            out += bytes(buf[self.HDR: self.HDR + (n - first)])
        self._set_counter(8, tail + n)
        return out

    def close(self) -> None:
        try:
            self._shm.close()
        except Exception:  # noqa: BLE001
            pass
        if self.owner:
            try:
                self._shm.unlink()
            except Exception:  # noqa: BLE001
                pass


def _ring_enabled() -> bool:
    return os.environ.get(SHARD_RING_ENV, "1").strip() != "0"


def _ring_bytes() -> int:
    try:
        return max(
            64 * 1024,
            int(os.environ.get(SHARD_RING_BYTES_ENV, _RING_DEFAULT_BYTES)),
        )
    except ValueError:
        return _RING_DEFAULT_BYTES


# --------------------------------------------------------------------- #
# Wire exception marshaling (proc transport)
# --------------------------------------------------------------------- #


def _exc_to_wire(e: BaseException) -> Tuple:
    from . import kube as kube_mod

    if isinstance(e, api.WebServerError):
        return ("wse", e.code, e.message)
    if isinstance(e, kube_mod.KubeAPIError):
        return ("kae", e.method, e.path, e.status, e.body)
    return ("exc", type(e).__name__, str(e))


def _exc_from_wire(w: Tuple) -> BaseException:
    from . import kube as kube_mod

    if w[0] == "wse":
        return api.WebServerError(w[1], w[2])
    if w[0] == "kae":
        return kube_mod.KubeAPIError(w[1], w[2], w[3], w[4])
    return RuntimeError(f"shard worker {w[1]}: {w[2]}")


class ShardWorkerError(RuntimeError):
    """A shard worker died, hung, or is administratively unavailable
    (distinct from an in-band scheduling error, which re-raises as its
    original type). Carries the forensic context the supervision plane
    journals: the worker's exitcode/signal (when a process actually
    exited), the verb that was in flight, and a cause classification —
    every instance is RETRIABLE by construction (the supervisor either
    resurrects the shard or holds it down; the caller's request was
    never half-applied because the worker executes strictly
    sequentially and replies before the parent observes completion)."""

    def __init__(
        self,
        message: str,
        shard_id: Optional[int] = None,
        method: str = "",
        cause: str = "died",
        exitcode: Optional[int] = None,
        signal_name: str = "",
    ):
        super().__init__(message)
        self.shard_id = shard_id
        self.method = method
        self.cause = cause  # died | hang | down | closed
        self.exitcode = exitcode
        self.signal_name = signal_name
        self.retriable = True


class ShardFrameError(RuntimeError):
    """One pipe frame failed to decode (truncated/garbage bytes). Fails
    only the affected call — the worker is alive and the byte stream is
    length-delimited by the Connection framing, so the reader loop keeps
    serving every other caller. Deliberately NOT a ShardWorkerError:
    the supervision plane must not resurrect a healthy worker over one
    corrupt frame."""


def _exit_signal_name(exitcode: Optional[int]) -> str:
    """Symbolic signal name for a negative Process.exitcode."""
    if exitcode is None or exitcode >= 0:
        return ""
    try:
        import signal as _signal

        return _signal.Signals(-exitcode).name
    except (ValueError, ImportError):
        return f"SIG{-exitcode}"


# --------------------------------------------------------------------- #
# Worker side
# --------------------------------------------------------------------- #


class _ForwardingKubeClient(KubeClient):
    """The worker's kube client: every call crosses the pipe to the
    parent, which executes it against the real client (with the parent's
    retry/fencing policy) — or against the per-shard partition store for
    the scheduler-owned ledger/snapshot state. Exceptions cross back and
    re-raise in place, so the framework's fault handling is unchanged."""

    def __init__(self, send: Callable, recv: Callable):
        self._send = send
        self._recv = recv

    def _rpc(self, method: str, *args):
        self._send(("kube", method, args))
        kind, payload = self._recv()
        if kind == "kube_err":
            raise _exc_from_wire(payload)
        return payload

    def bind_pod(self, binding_pod: Pod) -> None:
        self._rpc("bind_pod", binding_pod)

    def patch_pod_annotations(self, pod, annotations) -> None:
        self._rpc("patch_pod_annotations", pod, annotations)

    def evict_pod(self, pod) -> None:
        self._rpc("evict_pod", pod)

    def persist_scheduler_state(self, payload: str) -> None:
        self._rpc("persist_scheduler_state", payload)

    def load_scheduler_state(self):
        return self._rpc("load_scheduler_state")

    def persist_snapshot(self, chunks) -> None:
        self._rpc("persist_snapshot", chunks)

    def load_snapshot(self):
        return self._rpc("load_snapshot")


class ShardServer:
    """One shard's request executor: a full scheduler plus the staged-op
    table of the two-phase broadcast. Transport-agnostic — the proc
    worker loop and the local backend both drive it."""

    def __init__(
        self,
        config: Config,
        shard_id: int,
        owned_chains: Tuple[str, ...],
        kube_client: KubeClient,
        auto_admit: bool = False,
        plan: Optional[List[Tuple[str, ...]]] = None,
    ):
        self.shard_id = shard_id
        self.owned_chains = tuple(owned_chains)
        self.owned_set = set(owned_chains)
        # chain -> owning shard, from the full shard plan: health gauges
        # for a node whose chains span shards are accounted by exactly
        # ONE shard (the lowest owner) so the merged sums count it once.
        self.chain_shard: Dict[str, int] = {
            c: i
            for i, bucket in enumerate(plan or [owned_chains])
            for c in bucket
        }
        # Synchronous force-bind executor: a worker serves one request at
        # a time, so the bind re-entry must complete within the turn (the
        # async default would race the request loop on the pipe).
        # flight_recorder=False: under procShards the black-box recorder
        # captures at the FRONTEND (pre-routing) so one stream covers all
        # shards; the live auditor stays per-shard (each worker audits
        # its own core at the cadence).
        self.scheduler = HivedScheduler(
            config,
            kube_client=kube_client,
            force_bind_executor=lambda fn: fn(),
            auto_admit=auto_admit,
            flight_recorder=False,
        )
        self._staged: Dict[int, Tuple[str, tuple]] = {}
        # filter_fast's memoized suggested-node lists, keyed by the
        # parent-assigned id (see ShardedScheduler.filter_raw).
        self._nodes_cache: Dict = {}

    # -- two-phase broadcast (global mode) -------------------------- #

    def op_stage(self, op_id: int, method: str, args: tuple) -> bool:
        self._staged[op_id] = (method, args)
        return True

    def op_commit(self, op_id: int):
        method, args = self._staged.pop(op_id)
        return self.dispatch(method, args)

    def op_abort(self, op_id: int) -> bool:
        return self._staged.pop(op_id, None) is not None

    # -- shard-local verbs ------------------------------------------ #

    def ping(self) -> Dict:
        return {"shard": self.shard_id, "chains": list(self.owned_chains)}

    def seed_preempt_rng(self, seed: int) -> None:
        import random

        self.scheduler.core.preempt_rng = random.Random(seed)

    def filter_routine_raw(self, body: bytes, trace_parent=None) -> bytes:
        """The raw-bytes filter hot path: JSON decode/encode happens HERE,
        in the worker, so the parent's per-call GIL work is a route-cache
        hit and a pipe write — the parent must never become the serial
        bottleneck the GIL was (doc/hot-path.md "The multi-process
        contract"). Error semantics mirror the webserver's filter handler:
        protocol errors return in-band. ``trace_parent`` is the frontend
        trace id when the frontend sampled this request — the worker's
        trace commits as its child (causal cross-shard stitching)."""
        try:
            args = ei.ExtenderArgs.from_dict(json.loads(body))
            result = self.scheduler.filter_routine(
                args, trace_parent=trace_parent
            )
        except api.WebServerError as e:
            result = ei.ExtenderFilterResult(error=e.message)
        return json.dumps(result.to_dict()).encode()

    def filter_sweep(
        self, args: ei.ExtenderArgs, leaf_types, trace_parent=None
    ) -> ei.ExtenderFilterResult:
        """One chunk of the frontend's leaf-type-granular sweep: the
        any-leaf-type scan restricted to this shard's consecutive run of
        the global sorted leaf-type order (see the module docstring)."""
        return self.scheduler.filter_routine(
            args, leaf_types=tuple(leaf_types), trace_parent=trace_parent
        )

    def filter_sweep_raw(self, body: bytes, leaf_types,
                         trace_parent=None) -> bytes:
        """filter_sweep over the raw-bytes wire path (decode/encode in
        the worker, like filter_routine_raw)."""
        try:
            args = ei.ExtenderArgs.from_dict(json.loads(body))
            result = self.scheduler.filter_routine(
                args, leaf_types=tuple(leaf_types),
                trace_parent=trace_parent,
            )
        except api.WebServerError as e:
            result = ei.ExtenderFilterResult(error=e.message)
        return json.dumps(result.to_dict()).encode()

    def filter_fast(self, pod_dict: Dict, nodes_key, nodes,
                    trace_parent=None) -> Dict:
        """Node-list-memoized filter: the suggested-node list is by far
        the largest slice of every filter payload and is near-constant
        across calls (the default scheduler sends the same candidate set
        while the fleet is stable) — the parent sends it once per
        distinct set, then refers to it by key; a churned set arrives
        as a delta against a cached base (doc/hot-path.md "One wire").
        Returns the result DICT (packed small); the parent re-encodes
        for the HTTP reply."""
        if _is_delta_marker(nodes):
            base = self._nodes_cache.get(nodes[1])
            rebuilt = (
                _apply_suggested_delta(base, nodes)
                if base is not None else None
            )
            if rebuilt is None:
                # Base evicted, stale, or the frame failed its crc:
                # answer __needNodes and let the parent resync with the
                # full list — never filter against a guessed set.
                return {"__needNodes": True}
            if len(self._nodes_cache) > 64:
                self._nodes_cache.clear()
            nodes = self._nodes_cache[nodes_key] = rebuilt
        elif nodes is not None:
            if len(self._nodes_cache) > 64:
                self._nodes_cache.clear()
            nodes = self._nodes_cache[nodes_key] = list(nodes)
        else:
            nodes = self._nodes_cache.get(nodes_key)
            if nodes is None:
                # Evicted (or a restarted worker): the parent retries
                # with the full list.
                return {"__needNodes": True}
        if type(nodes_key) is tuple and len(nodes_key) == 2:
            # The parent's set id IS the PR-12 suggested-set token
            # (len, hash of the name tuple): seed the wait cache's
            # single-slot memo so the first token lookup for this list
            # object is O(1) instead of re-hashing the fleet. Parent
            # and worker hash() seeds differ, but tokens are opaque
            # equality values compared only inside this worker, and
            # this seeding keeps them consistent per list object.
            self.scheduler._suggested_token_memo = (
                nodes, len(nodes), nodes_key
            )
        try:
            # The MEMOIZED list object itself is handed to the filter
            # (not a per-call copy): filter_routine treats node_names as
            # read-only, and the stable identity lets the wait cache's
            # suggested-set token memo answer in O(1) per re-filter
            # instead of re-hashing the fleet-sized list (doc/hot-path.md
            # "Pending-pod plane" — the set id IS the object).
            args = ei.ExtenderArgs(
                pod=ei.pod_from_k8s(pod_dict), node_names=nodes
            )
            result = self.scheduler.filter_routine(
                args, trace_parent=trace_parent
            )
        except api.WebServerError as e:
            result = ei.ExtenderFilterResult(error=e.message)
        return result.to_dict()

    def whatif_stamp(self, items, horizon_s) -> int:
        """Stamp the frontend's MERGED queue forecast onto this shard's
        decision journal in one scan (shards never stamp their own
        queue-mode verdicts — see ShardedScheduler.whatif_routine)."""
        return self.scheduler.decisions.stamp_predicted_wait_groups(
            {gang_name: predicted for gang_name, predicted in items},
            horizon_s,
        )

    def delete_pod_meta(self, pod: Pod) -> Dict:
        """delete_pod + the group-liveness bit the parent's pin map
        needs (a vanished group releases its shard pin)."""
        self.scheduler.delete_pod(pod)
        try:
            name = extract_pod_scheduling_spec(pod).affinity_group.name
        except api.WebServerError:
            name = None
        live = (
            name is not None
            and name in self.scheduler.core.affinity_groups
        )
        return {"group": name, "groupLive": live}

    def delete_pods_meta(self, pods: List[Pod]) -> List[Dict]:
        """Bulk delete (drains, relist repairs): one RPC instead of one
        per pod."""
        return [self.delete_pod_meta(p) for p in pods]

    def get_status_pod(self, uid: str):
        """(pod, state) of one schedule status, None when unknown —
        the transport-agnostic slice of pod_schedule_statuses."""
        status = self.scheduler.pod_schedule_statuses.get(uid)
        if status is None:
            return None
        return status.pod, status.pod_state.value

    def list_state(self) -> Dict:
        """Routing-map rebuild after recovery: the pod uids and live
        group names this shard holds."""
        return {
            "uids": sorted(self.scheduler.pod_schedule_statuses),
            "groups": sorted(self.scheduler.core.affinity_groups),
        }

    def flush_snapshot(self, watermark) -> bool:
        self.scheduler.note_watermark(watermark)
        return self.scheduler.flush_snapshot_now()

    def recover_slice(self, nodes: List[Node], pods: List[Pod],
                      min_watermark=None) -> Dict:
        self.scheduler.recover(nodes, pods, min_watermark=min_watermark)
        return self.list_state()

    def replay_health_ticks(self, n: int) -> None:
        """Resurrection replay (scheduler.supervisor): advance the
        health clock by the supervisor journal's tick count so a
        resurrected shard's damper/clock state matches its never-crashed
        siblings — one RPC, worker-side loop."""
        for _ in range(int(n)):
            self.scheduler.health_tick()

    # -- positional inspect slices (merged by the parent) ----------- #

    def inspect_physical_positions(self) -> List[Tuple[int, Dict]]:
        """(index, status) for every position of the full config-ordered
        physical status list whose chain this shard owns. The position
        layout is config-determined (one entry per configured top cell,
        in chain -> config order), so the parent's merge-by-index
        reassembles exactly the single-process list — each position
        filled by the one shard whose state for it is authoritative."""
        fw = self.scheduler
        core = fw.core
        fw.get_physical_cluster_status()  # refresh the per-chain mirrors
        out: List[Tuple[int, Dict]] = []
        i = 0
        for chain in core.full_cell_list:
            statuses = core.physical_chain_status(chain)
            if chain in self.owned_set:
                out.extend(
                    (i + j, st) for j, st in enumerate(statuses)
                )
            i += len(statuses)
        return out

    def inspect_vc_positions(self, vcn: str) -> Tuple[List, List]:
        """The shard's slice of one VC's status: ``(indexed, appended)``.
        The static prefix (preassigned + pinned virtual cells) is
        config-positional like the physical list; the opportunistic-cell
        tail is allocation-history-shaped and merged order-normalized by
        the parent (sorted by cellAddress)."""
        core = self.scheduler.core
        statuses = self.scheduler.get_virtual_cluster_status(vcn)
        vcs = core.vc_schedulers[vcn]
        chain_of: List[str] = []
        for chain, ccl in vcs.non_pinned_preassigned.items():
            for level in sorted(ccl.levels):
                chain_of.extend([str(chain)] * len(ccl[level]))
        for ccl in vcs.pinned_cells.values():
            for c in ccl[ccl.top_level]:
                chain_of.append(str(c.chain))
        indexed: List[Tuple[int, Dict]] = []
        appended: List[Dict] = []
        # Opportunistic tail entries mirror _ot_cells insertion order;
        # the owning chain comes from the backing physical leaf (the
        # status address alone does not name its chain). This shard only
        # ever allocates OT cells in chains it owns, but filter anyway.
        tail_cells = list(core._ot_cells.get(vcn, {}).values())
        for i, st in enumerate(statuses):
            if i < len(chain_of):
                if chain_of[i] in self.owned_set:
                    indexed.append((i, st))
            else:
                j = i - len(chain_of)
                cell = tail_cells[j] if j < len(tail_cells) else None
                if cell is None or cell.chain in self.owned_set:
                    appended.append(st)
        return indexed, appended

    def _owned_node(self, name: str) -> bool:
        """True when THIS shard accounts for the node in merged health
        gauges/listings: the lowest shard owning any of its chains (a
        multi-family node is delivered to every owning shard, but summed
        merges must count it once)."""
        leaves = self.scheduler.core._node_leaf_index.get(name)
        if not leaves:
            # Unknown-to-config node: shard 0 alone accounts for it.
            return self.shard_id == 0
        owners = {
            self.chain_shard[leaf.chain]
            for leaf in leaves
            if leaf.chain in self.chain_shard
        }
        return bool(owners) and min(owners) == self.shard_id

    def get_metrics(self) -> Dict:
        """The scheduler's metrics with health GAUGES scoped to owned
        nodes: a shard never receives node events for foreign chains, so
        its core keeps those nodes in the constructor's all-bad
        bootstrap state — a partial-view artifact, not cluster truth."""
        m = self.scheduler.get_metrics()
        core = self.scheduler.core
        m["badNodeCount"] = sum(
            1 for n in core.bad_nodes if self._owned_node(n)
        )
        m["badChipCount"] = sum(
            len(c)
            for n, c in core.bad_chips.items()
            if self._owned_node(n)
        )
        m["drainingChipCount"] = sum(
            len(c)
            for n, c in core.draining_chips.items()
            if self._owned_node(n)
        )
        return m

    def get_doomed_ledger_owned(self) -> Dict:
        """The shard's doomed ledger filtered to owned chains: foreign
        chains sit in the all-bad bootstrap state and carry advisory
        dooms that are pure artifacts of the shard's partial view."""
        snap = self.scheduler.get_doomed_ledger()
        snap["vcs"] = {
            vcn: kept
            for vcn, entries in (snap.get("vcs") or {}).items()
            if (kept := [
                e for e in entries if e.get("chain") in self.owned_set
            ])
        }
        return snap

    def get_health_owned(self) -> Dict:
        """Health payload scoped to owned nodes (see get_metrics: the
        foreign all-bad bootstrap state is a partial-view artifact)."""
        payload = self.scheduler.get_health()
        payload["badNodes"] = [
            n for n in payload.get("badNodes") or []
            if self._owned_node(n)
        ]
        for key in ("badChips", "drainingChips"):
            payload[key] = {
                n: chips
                for n, chips in (payload.get(key) or {}).items()
                if self._owned_node(n)
            }
        return payload

    # -- dispatch ---------------------------------------------------- #

    def dispatch(self, method: str, args: tuple):
        fn = getattr(self, method, None)
        if fn is None:
            fn = getattr(self.scheduler, method)
        return fn(*args)


def _proc_worker_main(conn, config: Config, shard_id: int,
                      owned_chains: Tuple[str, ...], auto_admit: bool,
                      log_level: int,
                      plan: Optional[List[Tuple[str, ...]]] = None,
                      ring_names: Optional[Tuple[str, str]] = None,
                      wire_on: bool = True) -> None:
    """Entry point of a shard worker process: serve requests until the
    pipe closes. The protocol is PIPELINED — the parent may queue many
    requests before reading a reply, so the worker never idles waiting
    for the parent's wakeup between back-to-back requests (the stall
    that would otherwise cap a shard's throughput at the OS context-
    switch cadence rather than its compute). Execution stays strictly
    sequential in arrival order. A nested kube call blocks the current
    request; its reply is routed around any requests already queued in
    the pipe (``pending``)."""
    import collections

    common.init_logging(log_level)
    pending: collections.deque = collections.deque()
    closed = [False]
    req_ring = ShmRing(name=ring_names[0]) if ring_names else None
    resp_ring = ShmRing(name=ring_names[1]) if ring_names else None

    # One wire: both directions ride send_bytes/recv_bytes with the
    # frame packed by _pack_frame (binary, pickle fallback per frame)
    # and sniffed open by _unpack_frame. With wire_on=False every frame
    # is pickle — over send_bytes that is byte-identical to the
    # Connection.send/recv protocol the pre-wire code used, which is
    # what makes the HIVED_WIRE=0 A/B honest.
    def send(obj) -> None:
        buf, _codec = _pack_frame(obj, wire_on)
        conn.send_bytes(buf)

    def recv():
        # Decode failures are isolated from transport failures: a
        # truncated/garbage frame must fail only the affected request,
        # not kill the worker loop (pipe-protocol robustness; the
        # parent side mirrors this in ProcShardBackend._recv_frame).
        buf = conn.recv_bytes()
        try:
            return _unpack_frame(buf)
        except Exception as e:  # noqa: BLE001 — decode-only failure
            return ("__badframe__", f"{type(e).__name__}: {e}")

    def resolve(msg):
        # Ring frames MUST be consumed at pipe-arrival time (even when
        # the request is only buffered behind a nested kube call): the
        # ring carries payloads in pipe order, nothing else.
        if (
            req_ring is not None
            and isinstance(msg, tuple)
            and len(msg) == 3
            and isinstance(msg[2], tuple)
            and len(msg[2]) == 2
            and msg[2][0] == _RING_MARK
        ):
            return (msg[0], msg[1], _unpack_frame(req_ring.read(msg[2][1])))
        return msg

    def recv_kube_reply():
        # Drain queued requests into the local buffer until the kube
        # reply (a 2-tuple tagged kube_ok/kube_err) arrives.
        while True:
            msg = recv()
            if msg is None:
                closed[0] = True
                raise EOFError("parent closed mid kube call")
            if isinstance(msg, tuple) and msg and msg[0] in (
                "kube_ok", "kube_err"
            ):
                return msg
            if isinstance(msg, tuple) and msg and msg[0] == "__badframe__":
                # The corrupt frame may have BEEN the awaited kube
                # reply — notify the parent (it fails the oldest
                # pending call) and keep waiting; if the reply is truly
                # lost, the parent's verb deadline escalates this to
                # the supervision plane.
                send(("badframe", None, msg[1]))
                continue
            pending.append(resolve(msg))

    kube = _ForwardingKubeClient(send, recv_kube_reply)
    server = ShardServer(
        config, shard_id, owned_chains, kube, auto_admit=auto_admit,
        plan=plan,
    )
    while not closed[0]:
        if pending:
            msg = pending.popleft()
        else:
            try:
                msg = resolve(recv())
            except (EOFError, OSError):
                return
        if msg is None:
            return
        if isinstance(msg, tuple) and msg and msg[0] == "__badframe__":
            # A request frame that would not decode: report it (the
            # parent fails the oldest pending call with a decode
            # error) and keep serving — one corrupt frame must never
            # take the worker down.
            send(("badframe", None, msg[1]))
            continue
        req_id, method, args = msg
        if method == "__debug__":
            # Test-only fault injection (supervision/robustness tests):
            # "raw" writes arbitrary bytes straight onto the pipe
            # (garbage-frame injection), "sleep" wedges the worker
            # mid-verb (hang detection).
            op = args[0]
            if op == "raw":
                conn.send_bytes(args[1])
            elif op == "sleep":
                import time as _time

                _time.sleep(args[1])
            send(("ok", req_id, True))
            continue
        try:
            result = server.dispatch(method, args)
        except BaseException as e:  # noqa: BLE001
            send(("err", req_id, _exc_to_wire(e)))
        else:
            if wire_on and method == "filter_fast" and type(result) is dict:
                # The filter reply is JSON-born (ExtenderFilterResult
                # .to_dict), so the frame may ship it as one C-speed
                # json blob instead of an element walk. Method-gated:
                # an arbitrary result dict could carry int keys, which
                # Json would silently stringify.
                result = wire_mod.Json(result)
            sent = False
            if (
                resp_ring is not None
                and method in _RING_METHODS
                # O(1) size hint before the speculative encode: only
                # byte/str results can be cheaply sized, and they are
                # exactly the potentially-large replies
                # (filter_routine_raw's encoded body); filter_fast's
                # small result dicts keep the pipe.
                and isinstance(result, (bytes, bytearray, str))
                and len(result) >= _RING_MIN_BYTES
            ):
                try:
                    payload, _codec = _pack_frame(result, wire_on)
                except Exception:  # noqa: BLE001 — fall through to pipe
                    payload = None
                if (
                    payload is not None
                    and len(payload) >= _RING_MIN_BYTES
                    and resp_ring.try_write(payload)
                ):
                    send(("ok", req_id, (_RING_MARK, len(payload))))
                    sent = True
            if not sent:
                try:
                    send(("ok", req_id, result))
                except Exception:  # noqa: BLE001 — unencodable result
                    send(("err", req_id, (
                        "exc", "TypeError",
                        f"unencodable result from {method}",
                    )))


# --------------------------------------------------------------------- #
# Parent-side backends
# --------------------------------------------------------------------- #

# Per-verb pipe deadline (supervision plane, doc/fault-model.md "Shard
# supervision plane"): a worker that stops draining the pipe — wedged in
# native code, deadlocked, livelocked — trips the SAME failure path as a
# dead one: the waiting caller SIGKILLs the worker and fails all
# in-flight calls retriably, and the supervisor resurrects the shard.
# Verbs that legitimately run long (recovery replay, snapshot flush,
# what-if horizon replay) get a 10x allowance. "0" disables deadlines
# (the pre-supervision blocking behavior).
SHARD_DEADLINE_ENV = "HIVED_SHARD_VERB_DEADLINE_S"
_DEADLINE_DEFAULT_S = 60.0
_SLOW_VERB_FACTOR = 10.0
_SLOW_VERBS = frozenset({
    "recover_slice", "prefetch_snapshot", "flush_snapshot",
    "whatif_routine", "op_stage", "op_commit", "list_state",
})


def _verb_deadline_default() -> float:
    try:
        return float(
            os.environ.get(SHARD_DEADLINE_ENV) or _DEADLINE_DEFAULT_S
        )
    except ValueError:
        return _DEADLINE_DEFAULT_S


class _VerbDeadline(Exception):
    """Internal: a caller's per-verb pipe deadline expired."""


class LocalShardBackend:
    """In-process shard: the identical ShardServer protocol without the
    pipe — used by the chaos harness (deep inspection) and anywhere the
    protocol itself is under test."""

    def __init__(self, server: ShardServer):
        self.server = server
        self.shard_id = server.shard_id
        self.owned_chains = server.owned_chains
        self._lock = threading.Lock()
        self._dead = False
        self.last_exit: Optional[Dict] = None

    @property
    def scheduler(self) -> HivedScheduler:
        return self.server.scheduler

    def is_alive(self) -> bool:
        return not self._dead

    def kill(self, cause: str = "kill") -> None:
        """Death emulation for the supervision chaos events: subsequent
        calls raise exactly the ShardWorkerError the proc transport
        raises, so the frontend's degraded-mode and resurrection paths
        run unchanged. cause="hang" emulates a wedged worker tripped by
        the verb deadline (same terminal state: the supervisor kills a
        hung worker before respawning it)."""
        with self._lock:
            self._dead = True
            self.last_exit = {
                "cause": cause,
                "exitcode": None if cause == "hang" else -9,
                "signal": "" if cause == "hang" else "SIGKILL",
                "method": "",
                "methods": [],
            }

    def call(self, method: str, *args, timeout: Optional[float] = None):
        with self._lock:
            if self._dead:
                cause = (self.last_exit or {}).get("cause", "died")
                raise ShardWorkerError(
                    f"shard {self.shard_id} worker {cause} ({method})",
                    shard_id=self.shard_id,
                    method=method,
                    cause="died" if cause == "kill" else cause,
                    exitcode=(self.last_exit or {}).get("exitcode"),
                    signal_name=(self.last_exit or {}).get("signal", ""),
                )
            return self.server.dispatch(method, args)

    def close(self) -> None:
        pass


class ProcShardBackend:
    """A shard worker behind a duplex pipe in its own OS process.

    The protocol is PIPELINED: any number of parent threads may have
    calls in flight to one shard — requests queue in the pipe, the
    worker executes them strictly sequentially, and a reader thread
    routes replies back to the waiting callers by request id. A shard
    under load therefore runs back-to-back with no parent-wakeup stall
    between requests, and requests to DIFFERENT shards run genuinely in
    parallel — that is the point. Nested kube calls from the worker are
    serviced on the reader thread (the worker is blocked on that very
    call, so no replies can be queued behind it from this shard)."""

    def __init__(
        self,
        config: Config,
        shard_id: int,
        owned_chains: Tuple[str, ...],
        kube_handler: Callable[[str, tuple], object],
        auto_admit: bool,
        plan: Optional[List[Tuple[str, ...]]] = None,
        use_ring: Optional[bool] = None,
        use_wire: Optional[bool] = None,
    ):
        import multiprocessing as mp

        method = os.environ.get(PROC_START_ENV) or "spawn"
        ctx = mp.get_context(method)
        self.shard_id = shard_id
        self.owned_chains = tuple(owned_chains)
        self._kube_handler = kube_handler
        self._send_lock = threading.Lock()
        self._wire_on = _wire_enabled() if use_wire is None else use_wire
        # Per-codec transport telemetry (both directions, pipe + ring),
        # merged into wireBytesTotal / shardWire by the frontend. Sends
        # are counted under _send_lock and receives by the (single)
        # leader; _stats_lock covers the cross-thread dict updates.
        self._stats_lock = threading.Lock()
        self.wire_bytes: Dict[str, int] = {"binary": 0, "pickle": 0}
        self.frame_hist: Dict[str, Dict[int, int]] = {}
        # Shared-memory filter ring (one per direction; see ShmRing).
        if use_ring is None:
            use_ring = _ring_enabled()
        self._req_ring: Optional[ShmRing] = None
        self._resp_ring: Optional[ShmRing] = None
        if use_ring:
            try:
                self._req_ring = ShmRing(size=_ring_bytes())
                self._resp_ring = ShmRing(size=_ring_bytes())
            except Exception:  # noqa: BLE001 — no shm: pipe payloads
                if self._req_ring is not None:
                    self._req_ring.close()
                self._req_ring = self._resp_ring = None
        self.ring_frames = 0
        self.ring_fallbacks = 0
        # Leader/follower receive: exactly one in-flight caller (the
        # "leader") blocks in conn.recv and dispatches whatever arrives
        # — its own reply, another caller's (delivered to that caller's
        # PERSONAL event: one targeted wakeup per reply, never a herd),
        # or a nested kube call. On exit the leader hands leadership to
        # exactly one reply-less waiter. No dedicated reader thread: the
        # single-in-flight fast path costs one send + one recv wakeup,
        # the same two context switches a plain lock-per-call protocol
        # pays, while still allowing arbitrary pipelining depth.
        self._io_lock = threading.Lock()
        self._reader_busy = False
        self._pending: Dict[int, List] = {}
        self._closing = False
        self._closed = False
        self._dead = False
        # Supervision plane: last_exit records WHY the worker stopped
        # (cause, exitcode, symbolic signal, in-flight verbs) the first
        # time the backend observes death — never overwritten, so the
        # journaled record is the original cause even when multiple
        # callers race the discovery.
        self.last_exit: Optional[Dict] = None
        self._deadline_s = _verb_deadline_default()
        self._conn, child = ctx.Pipe(duplex=True)
        ring_names = (
            (self._req_ring.name, self._resp_ring.name)
            if self._req_ring is not None
            else None
        )
        self._proc = ctx.Process(
            target=_proc_worker_main,
            args=(
                child, config, shard_id, self.owned_chains, auto_admit,
                common.log.getEffectiveLevel(), plan, ring_names,
                self._wire_on,
            ),
            name=f"hived-shard-{shard_id}",
            daemon=True,
        )
        self._proc.start()
        child.close()
        self._req_seq = itertools.count()

    def _note_frame(self, codec: str, nbytes: int) -> None:
        with self._stats_lock:
            self.wire_bytes[codec] = (
                self.wire_bytes.get(codec, 0) + nbytes
            )
            h = self.frame_hist.setdefault(codec, {})
            b = nbytes.bit_length()
            h[b] = h.get(b, 0) + 1

    def _send_frame(self, obj) -> None:
        """Pack + send one control frame under _send_lock, counting its
        codec and size."""
        buf, codec = _pack_frame(obj, self._wire_on)
        with self._send_lock:
            self._conn.send_bytes(buf)
        self._note_frame(codec, len(buf))

    def _recv_frame(self):
        """Leader-side receive: one frame off the pipe, sniffed,
        counted, decoded. Transport failures (EOFError/OSError from the
        pipe itself) mean the worker is gone; a DECODE failure of an
        otherwise well-framed message raises ShardFrameError instead —
        the worker is alive, only this one frame is garbage."""
        buf = self._conn.recv_bytes()
        self._note_frame(
            "binary" if wire_mod.is_wire(buf) else "pickle", len(buf)
        )
        try:
            return _unpack_frame(buf)
        except Exception as e:  # noqa: BLE001 — decode-only failure
            raise ShardFrameError(
                f"shard {self.shard_id}: undecodable pipe frame "
                f"({len(buf)} bytes): {type(e).__name__}: {e}"
            ) from e

    def _dispatch_msg(self, msg) -> None:
        if msg[0] == "badframe":
            # The worker could not decode one request frame: fail the
            # oldest pending call (the worker serves strictly in
            # arrival order, so the corrupt frame is at the head of its
            # queue) and keep everything else in flight.
            with self._io_lock:
                self._fail_oldest_locked(msg[2])
            return
        if msg[0] == "kube":
            _, kmethod, kargs = msg
            try:
                result = self._kube_handler(kmethod, kargs)
            except BaseException as e:  # noqa: BLE001
                reply = ("kube_err", _exc_to_wire(e))
            else:
                reply = ("kube_ok", result)
            self._send_frame(reply)
            return
        kind, rid, payload = msg
        if (
            kind == "ok"
            and self._resp_ring is not None
            and isinstance(payload, tuple)
            and len(payload) == 2
            and payload[0] == _RING_MARK
        ):
            # Resolve ring payloads at pipe-arrival time UNCONDITIONALLY
            # (even for a vanished caller): the ring is ordered by pipe
            # order, so the bytes must be consumed here or never.
            raw = self._resp_ring.read(payload[1])
            self._note_frame(
                "binary" if wire_mod.is_wire(raw) else "pickle", len(raw)
            )
            payload = _unpack_frame(raw)
        with self._io_lock:
            slot = self._pending.pop(rid, None)
        if slot is not None:
            slot[1] = (kind, payload)
            slot[0].set()

    def _fail_all_locked(self, cause: str = "died",
                         method: str = "") -> None:
        if self._dead:
            return  # first observer wins: keep the original cause
        self._dead = True
        exitcode = self._proc.exitcode
        methods = sorted({
            s[2] for s in self._pending.values() if len(s) > 2 and s[2]
        })
        self.last_exit = {
            "cause": cause,
            "exitcode": exitcode,
            "signal": _exit_signal_name(exitcode),
            "method": method or (methods[0] if methods else ""),
            "methods": methods,
        }
        pending, self._pending = dict(self._pending), {}
        for slot in pending.values():
            slot[1] = ("died", None)
            slot[0].set()

    def _fail_oldest_locked(self, detail: str) -> None:
        """One undecodable frame fails exactly one call: the oldest
        pending request, because the worker executes (and therefore
        replies) strictly in arrival order. Approximation caveat: with
        concurrent senders arrival order can differ from request-id
        order by in-flight races, but the affected window is the calls
        racing the corruption — never a strand, never a poisoned loop."""
        if not self._pending:
            return
        rid = min(self._pending)
        slot = self._pending.pop(rid)
        slot[1] = ("frame_err", detail)
        slot[0].set()

    def is_alive(self) -> bool:
        if self._dead:
            return False
        if self._proc.is_alive():
            return True
        # Silent death discovered by the liveness probe (no caller has
        # touched the pipe yet): latch the forensic record — exitcode,
        # symbolic signal — and fail any in-flight stragglers through
        # the same terminal path a caller's pipe error takes.
        with self._io_lock:
            self._fail_all_locked()
        return False

    def kill(self, cause: str = "kill") -> None:
        """SIGKILL the worker and fail all in-flight calls retriably.
        Used by the supervisor's hang trip and by fault-injection
        tests; resurrection is the supervisor's job, not ours."""
        try:
            self._proc.kill()
        except Exception:  # noqa: BLE001 — already gone
            pass
        self._proc.join(timeout=5)
        with self._io_lock:
            self._fail_all_locked(cause=cause)

    def _trip_hang(self, method: str) -> None:
        common.log.warning(
            "shard %d worker hung in %r (deadline %.1fs): killing",
            self.shard_id, method, self._deadline_s,
        )
        try:
            self._proc.kill()
        except Exception:  # noqa: BLE001
            pass
        self._proc.join(timeout=5)
        with self._io_lock:
            self._fail_all_locked(cause="hang", method=method)

    def _handoff_locked(self) -> None:
        """Wake exactly one reply-less waiter to take over reading (it
        sees its result still unset and claims leadership)."""
        for slot in self._pending.values():
            if slot[1] is None:
                slot[0].set()
                return

    def call(self, method: str, *args, timeout: Optional[float] = None):
        import time as _time

        deadline_s = self._deadline_s if timeout is None else timeout
        if deadline_s and method in _SLOW_VERBS and timeout is None:
            deadline_s *= _SLOW_VERB_FACTOR
        deadline_at = (
            _time.monotonic() + deadline_s if deadline_s else None
        )
        req_id = next(self._req_seq)
        slot: List = [threading.Event(), None, method]
        with self._io_lock:
            if self._closing or self._dead:
                exit_info = self.last_exit or {}
                raise ShardWorkerError(
                    f"shard {self.shard_id} backend is "
                    f"{'dead' if self._dead else 'closed'} ({method})",
                    shard_id=self.shard_id,
                    method=method,
                    cause=exit_info.get(
                        "cause", "died" if self._dead else "closed"
                    ),
                    exitcode=exit_info.get("exitcode"),
                    signal_name=exit_info.get("signal", ""),
                )
            self._pending[req_id] = slot
        try:
            ring_note = None
            with self._send_lock:
                # Ring write + control send under ONE lock hold: pipe
                # order must equal ring order across caller threads.
                wire_args = args
                if (
                    self._req_ring is not None
                    and method in _RING_METHODS
                    and _ring_candidate_args(method, args)
                ):
                    payload, pcodec = _pack_frame(args, self._wire_on)
                    if len(payload) < _RING_MIN_BYTES:
                        pass  # small frame: the pipe's one copy is cheaper
                    elif self._req_ring.try_write(payload):
                        wire_args = (_RING_MARK, len(payload))
                        self.ring_frames += 1
                        ring_note = (pcodec, len(payload))
                    else:
                        self.ring_fallbacks += 1
                buf, codec = _pack_frame(
                    (req_id, method, wire_args), self._wire_on
                )
                self._conn.send_bytes(buf)
            self._note_frame(codec, len(buf))
            if ring_note is not None:
                self._note_frame(*ring_note)
        except (OSError, ValueError) as e:
            with self._io_lock:
                self._pending.pop(req_id, None)
                if isinstance(e, OSError):
                    # Broken pipe on send: the worker is gone. (A
                    # ValueError is a frame-size problem, not death.)
                    self._fail_all_locked(method=method)
            raise ShardWorkerError(
                f"shard {self.shard_id} worker died mid-call "
                f"({method}): {e}",
                shard_id=self.shard_id,
                method=method,
                exitcode=(self.last_exit or {}).get("exitcode"),
                signal_name=(self.last_exit or {}).get("signal", ""),
            ) from e
        leading = False
        while slot[1] is None:
            if not leading:
                with self._io_lock:
                    if slot[1] is not None:
                        break
                    if not self._reader_busy:
                        self._reader_busy = leading = True
                if not leading:
                    # Follower: sleep until my reply lands or I am
                    # handed leadership (event set, result still None).
                    slot[0].wait(0.2)
                    slot[0].clear()
                    if (
                        slot[1] is None
                        and deadline_at is not None
                        and _time.monotonic() > deadline_at
                    ):
                        # My verb deadline expired while someone else
                        # leads: the worker stopped draining the pipe.
                        # Kill it; _fail_all_locked sets every slot
                        # (including mine), and the leader EOFs out.
                        self._trip_hang(method)
                    continue
            # Leader: read + dispatch one message, keep leading until my
            # own reply arrives, then hand off to one waiter.
            try:
                while not self._conn.poll(0.2):
                    if (
                        deadline_at is not None
                        and _time.monotonic() > deadline_at
                    ):
                        raise _VerbDeadline()
                msg = self._recv_frame()
            except (EOFError, OSError):
                with self._io_lock:
                    self._reader_busy = False
                    self._fail_all_locked(method=method)
                break
            except _VerbDeadline:
                self._trip_hang(method)
                break
            except ShardFrameError as e:
                # Garbage frame: fail the oldest pending call only and
                # keep leading — the stream is length-delimited, so the
                # next frame decodes independently.
                with self._io_lock:
                    self._fail_oldest_locked(str(e))
                continue
            self._dispatch_msg(msg)
        with self._io_lock:
            if leading:
                self._reader_busy = False
            if not self._reader_busy:
                # Hand leadership to one reply-less waiter (also covers
                # the corner where a handed-off waiter's reply raced in
                # and it exited without ever leading).
                self._handoff_locked()
        kind, payload = slot[1]
        if kind == "died":
            exit_info = self.last_exit or {}
            cause = exit_info.get("cause", "died")
            raise ShardWorkerError(
                f"shard {self.shard_id} worker {cause} mid-call "
                f"({method}; exitcode={exit_info.get('exitcode')}"
                f"{' ' + exit_info['signal'] if exit_info.get('signal') else ''})",
                shard_id=self.shard_id,
                method=method,
                cause=cause,
                exitcode=exit_info.get("exitcode"),
                signal_name=exit_info.get("signal", ""),
            )
        if kind == "frame_err":
            raise ShardFrameError(
                f"shard {self.shard_id} call ({method}) lost to an "
                f"undecodable pipe frame: {payload}"
            )
        if kind == "err":
            raise _exc_from_wire(payload)
        return payload

    def close(self) -> None:
        # Idempotent, and safe against a worker that is already dead
        # (the close-races-death path): every step below tolerates a
        # closed pipe / exited process, and the _closed latch makes a
        # second close a no-op — including the supervisor closing a
        # backend the frontend's own close() later sweeps again.
        with self._io_lock:
            if self._closed:
                return
            self._closed = True
            self._closing = True
        try:
            with self._send_lock:
                self._conn.send(None)
        except (OSError, ValueError):
            pass
        self._proc.join(timeout=5)
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=5)
        if self._proc.is_alive():
            # A worker wedged past SIGTERM (the hang failure mode
            # close can race): escalate so rings/pipes never leak.
            try:
                self._proc.kill()
            except Exception:  # noqa: BLE001
                pass
            self._proc.join(timeout=5)
        try:
            self._conn.close()
        except OSError:
            pass
        for ring in (self._req_ring, self._resp_ring):
            if ring is not None:
                ring.close()
        self._req_ring = self._resp_ring = None


# --------------------------------------------------------------------- #
# Partitioned durable-state stores
# --------------------------------------------------------------------- #


class _PartitionStore:
    """Per-shard slots multiplexed onto the single underlying scheduler
    ConfigMap blobs. Per-chain disjointness is what makes mixed-age slots
    safe: each shard recovers its own chains from its own slot, and no
    cross-slot consistency is required. A partition-fingerprint mismatch
    (different shard plan, or a single-process blob) invalidates every
    slot — recovery falls back to the full annotation replay."""

    def __init__(self, kube_client: KubeClient, fingerprint: str):
        self.kube = kube_client
        self.fingerprint = fingerprint
        self._lock = threading.Lock()
        self._ledgers: Dict[str, str] = {}
        self._snapshots: Dict[str, List[str]] = {}
        self._loaded = False

    def _load_locked(self) -> None:
        """Populate the slot maps from the stored envelopes. Read faults
        PROPAGATE and leave _loaded False: caching a failed read would
        make the next persist rewrite the merged blob from an empty
        in-memory view, durably erasing every other shard's slot that
        still exists remotely. Callers absorb the raise exactly like the
        single-process paths do (recovery loads degrade to full replay;
        persists count an advisory write failure and retry next flush).
        """
        if self._loaded:
            return
        blob = self.kube.load_scheduler_state()
        env = _decode_envelope(blob, self.fingerprint)
        self._ledgers = dict(env) if env is not None else {}
        chunks = self.kube.load_snapshot()
        self._snapshots = _split_snapshot(chunks, self.fingerprint)
        self._loaded = True

    def load_ledger(self, shard_id: int) -> Optional[str]:
        with self._lock:
            self._load_locked()
            return self._ledgers.get(str(shard_id))

    def persist_ledger(self, shard_id: int, payload: str) -> None:
        # The kube write stays INSIDE the store lock (the single-process
        # _ledger_write_lock discipline): two concurrent shard persists
        # otherwise race the merged blob onto the ConfigMap out of order
        # and the loser's slot is durably lost. This lock is a private
        # store mutex — never a scheduler chain lock — so holding it
        # across the write serializes only competing persists.
        with self._lock:
            self._load_locked()
            self._ledgers[str(shard_id)] = payload
            blob = json.dumps({
                _ENVELOPE_KEY: self.fingerprint,
                "ledgers": self._ledgers,
            })
            self.kube.persist_scheduler_state(blob)

    def load_snapshot(self, shard_id: int) -> Optional[List[str]]:
        with self._lock:
            self._load_locked()
            chunks = self._snapshots.get(str(shard_id))
            return list(chunks) if chunks is not None else None

    def persist_snapshot(self, shard_id: int, chunks: List[str]) -> None:
        with self._lock:  # see persist_ledger: write under the store lock
            self._load_locked()
            self._snapshots[str(shard_id)] = list(chunks)
            merged = _merge_snapshot(self._snapshots, self.fingerprint)
            self.kube.persist_snapshot(merged)


def _decode_envelope(blob, fingerprint: str) -> Optional[Dict[str, str]]:
    if not blob:
        return None
    try:
        d = json.loads(blob)
    except (TypeError, ValueError):
        return None
    if not isinstance(d, dict) or d.get(_ENVELOPE_KEY) != fingerprint:
        return None
    ledgers = d.get("ledgers")
    return dict(ledgers) if isinstance(ledgers, dict) else None


def _merge_snapshot(
    snapshots: Dict[str, List[str]], fingerprint: str
) -> List[str]:
    """One chunk list for the underlying store: a directory chunk naming
    each shard's chunk count AND slot checksum, then the shard chunk
    groups in shard-id order. Splittable without decoding any shard's own
    chunks. The per-slot sha256 makes each slot a SECTION in the
    durable-state-plane-v2 sense: corruption localizes to the slot it
    hit, and only that shard falls back to replaying its chains."""
    order = sorted(snapshots, key=int)
    directory = json.dumps({
        _ENVELOPE_KEY: fingerprint,
        "shards": {k: len(snapshots[k]) for k in order},
        "sha256": {
            k: hashlib.sha256(
                "".join(snapshots[k]).encode()
            ).hexdigest()
            for k in order
        },
    })
    merged = [directory]
    for k in order:
        merged.extend(snapshots[k])
    return merged


def _split_snapshot(chunks, fingerprint: str) -> Dict[str, List[str]]:
    """Split the merged blob back into per-shard slots. A slot that
    fails its directory checksum is kept but flagged in the log: the
    slot's OWN sectioned envelope (manifest + per-family checksums) is
    the authority on what inside it is salvageable, so passing it
    through lets the shard recover partially instead of replaying
    wholesale. Only a short slice drops the slot — past a truncation the
    boundary is unknowable. An unusable DIRECTORY (unparseable, wrong
    partition fingerprint) still invalidates everything. Directories
    from before the per-slot checksum (one schema back) split by counts
    alone."""
    if not chunks:
        return {}
    try:
        directory = json.loads(chunks[0])
        if (
            not isinstance(directory, dict)
            or directory.get(_ENVELOPE_KEY) != fingerprint
        ):
            return {}
        counts = directory["shards"]
        shas = directory.get("sha256") or {}
    except Exception:  # noqa: BLE001 — no directory: no partitions
        return {}
    out: Dict[str, List[str]] = {}
    i = 1
    for k in sorted(counts, key=int):
        try:
            n = int(counts[k])
        except (TypeError, ValueError):
            return {}  # boundary unknowable past this point
        slot = list(chunks[i:i + n])
        i += n
        if len(slot) != n:
            common.log.warning(
                "partition snapshot slot %s truncated (%d/%d chunks); "
                "dropping the slot — shard falls back to replay", k,
                len(slot), n,
            )
            continue
        want = shas.get(k)
        if want is not None and hashlib.sha256(
            "".join(slot).encode()
        ).hexdigest() != want:
            common.log.warning(
                "partition snapshot slot %s failed its checksum; passing "
                "it through — the slot's own section ladder localizes "
                "the damage", k,
            )
        out[k] = slot
    return out


class _ShardScopedKubeClient(KubeClient):
    """The kube client a LOCAL shard's scheduler holds (the proc
    transport routes the same calls through the pipe to
    ``ShardedScheduler._handle_kube``): cluster writes go to the shared
    client behind the frontend's leadership fence, scheduler-owned state
    goes to this shard's partition slot."""

    def __init__(self, frontend: "ShardedScheduler", shard_id: int):
        self.frontend = frontend
        self.shard_id = shard_id

    def bind_pod(self, binding_pod: Pod) -> None:
        self.frontend._handle_kube("bind_pod", (binding_pod,))

    def patch_pod_annotations(self, pod, annotations) -> None:
        self.frontend._handle_kube(
            "patch_pod_annotations", (pod, annotations)
        )

    def evict_pod(self, pod) -> None:
        self.frontend._handle_kube("evict_pod", (pod,))

    def persist_scheduler_state(self, payload: str) -> None:
        self.frontend.store.persist_ledger(self.shard_id, payload)

    def load_scheduler_state(self):
        return self.frontend.store.load_ledger(self.shard_id)

    def persist_snapshot(self, chunks) -> None:
        self.frontend.store.persist_snapshot(self.shard_id, chunks)

    def load_snapshot(self):
        return self.frontend.store.load_snapshot(self.shard_id)


# --------------------------------------------------------------------- #
# The frontend
# --------------------------------------------------------------------- #


class ShardedScheduler:
    """The multi-process scheduling frontend: the :class:`HivedScheduler`
    surface (extender verbs, informer event handlers, recovery, inspect)
    over N per-chain-family shard backends. See the module docstring for
    the contract; ``doc/hot-path.md`` "The multi-process contract" for
    the measured numbers."""

    def __init__(
        self,
        config: Config,
        kube_client: Optional[KubeClient] = None,
        n_shards: Optional[int] = None,
        transport: str = "proc",
        auto_admit: bool = False,
    ):
        self.config = config
        self._kube_client = kube_client or NullKubeClient()
        self.auto_admit = auto_admit
        if n_shards is None:
            n_shards = int(os.environ.get(PROC_SHARDS_ENV, "0") or 0)
        self.routing = RoutingTable(config)
        plan = self.routing.shard_plan(max(1, n_shards))
        self.store = _PartitionStore(
            self.kube_client, self.routing.fingerprint(plan)
        )
        self.transport = transport
        self._plan = plan
        self.shards: List = []
        for sid, owned in enumerate(plan):
            self.shards.append(self._spawn_backend(sid, owned))
        self._shard_of_chain: Dict[str, int] = {}
        for sid, backend in enumerate(self.shards):
            for c in backend.owned_chains:
                self._shard_of_chain[c] = sid
        # Leaf-type-granular sweep chunks (module docstring): the global
        # sorted leaf-type order, chunked into maximal consecutive runs
        # owned by one shard. The chunks partition the in-process scan,
        # so probing them in order IS the in-process probe order.
        self._sweep_chunks: List[Tuple[int, Tuple[str, ...]]] = []
        for leaf in sorted(self.routing.leaf_chains):
            chains = self.routing.leaf_chains[leaf]
            sid = self._shard_of_chain.get(chains[0])
            if sid is None:
                continue
            if self._sweep_chunks and self._sweep_chunks[-1][0] == sid:
                prev_sid, prev = self._sweep_chunks[-1]
                self._sweep_chunks[-1] = (prev_sid, prev + (leaf,))
            else:
                self._sweep_chunks.append((sid, (leaf,)))
        # Routing memory: group name -> shard (pinned at first route so a
        # mixed-SKU gang stays on the shard its group registered in), and
        # pod uid -> shard (bind/delete args may carry no routable spec).
        # Guarded by _maps_lock; entries die with the group/pod and are
        # rebuilt from the shards after recovery.
        self._maps_lock = threading.Lock()
        self._group_shard: Dict[str, int] = {}
        self._uid_shard: Dict[str, int] = {}
        # Routing-decision cache: (spec annotation, node name) ->
        # (shard-or-None, group name). The chain derivation is a pure
        # function of the config and those two strings, so a hit skips
        # the YAML spec decode entirely (the dominant parent-side cost
        # per routed call); the group-pin map is still consulted on
        # every hit — a pin always outranks the chain derivation.
        self._route_cache: Dict[Tuple[str, str], Tuple[Optional[int], Optional[str]]] = {}
        # filter_fast node-list memo bookkeeping: distinct suggested-node
        # sets get a parent-assigned id; each shard is sent the full list
        # once per id and refers to it by id afterwards (the node list is
        # the dominant slice of a filter payload at fleet scale). The id
        # is the PR-12 suggested-set token (len, hash) — one memo serves
        # the transport, the delta base reference, and the worker-side
        # wait-cache token seed (doc/hot-path.md "One wire").
        self._nodes_ids: Dict[Tuple[str, ...], Tuple[int, int]] = {}
        self._nodes_sent: List[Set[Tuple[int, int]]] = [
            set() for _ in range(len(self.shards))
        ]
        # Delta-encoded suggested sets: per-shard last fully-held set
        # (id, tuple) to diff against, a single-slot transition memo
        # (every shard sees the same fleet transition, so the O(fleet)
        # edit script is computed once), and the resync counter.
        self._wire_on = _wire_enabled()
        self._nodes_acked: List[Optional[Tuple]] = [
            None for _ in range(len(self.shards))
        ]
        self._delta_memo: Optional[Tuple] = None
        self._delta_resyncs = 0
        # HTTP envelope bytes by codec (the pipe/ring frame bytes are
        # counted per backend; this is the frontend's own wire).
        self._wire_env_bytes = {"json": 0, "binary": 0}
        self._op_seq = itertools.count(1)
        self._op_lock = threading.Lock()
        self._watermark = 0
        self._ready = threading.Event()
        if auto_admit:
            self._ready.set()
        self.leadership = None
        self._deposed_bind_refused = 0
        self._deposed_drop_logged = False
        self._flusher_stop: Optional[threading.Event] = None
        self._flusher_thread: Optional[threading.Thread] = None
        # Informer-boot capture (see begin_recovery): while the informer
        # replays its initial lists, node events are buffered and the
        # whole replay fans out at finish_recovery.
        self._informer_capture: Optional[Dict] = None
        # The informer forces recovery traces; the frontend's own ring
        # carries them (workers keep their own per-shard rings), and its
        # FILTER traces are the causal parents worker traces stitch under
        # in the merged /v1/inspect/traces.
        from . import tracing as tracing_mod

        self.tracer = tracing_mod.Tracer(
            sample=None, capacity=config.trace_ring_capacity
        )
        # Black-box flight recorder, FRONTEND capture (pre-routing): one
        # stream covers all shards. Frontend windows anchor only at boot
        # (pristine) — merging mid-run anchors across shard projections
        # is a recorded follow-on (scheduler.recorder module docstring).
        from . import recorder as recorder_mod
        from . import snapshot as snapshot_mod

        self.recorder = None
        if (
            config.flight_recorder_capacity > 0
            and os.environ.get(
                recorder_mod.FLIGHT_RECORDER_ENV, "1"
            ).strip() != "0"
        ):
            self.recorder = recorder_mod.FlightRecorder(
                capacity=config.flight_recorder_capacity,
                exporter=None,
                config_fingerprint=snapshot_mod.config_fingerprint(
                    config
                ),
                granularity="frontend",
            )
            self.recorder.set_node_universe(
                self.configured_node_names()
            )
        # Nested-verb guard for the recorder (update_pod's delete+add
        # degrade must not double-record).
        self._rec_nested = threading.local()
        # Frontend-owned decision journal: supervision lifecycle records
        # (`_shard` source) and degraded-mode WAIT verdicts are journaled
        # HERE — the shard that would normally journal them is the one
        # that is down. Merged into /v1/inspect/decisions.
        from . import decisions as decisions_mod

        self.decisions = decisions_mod.DecisionJournal(
            capacity=config.decision_journal_capacity
        )
        # The shard supervision plane (scheduler.supervisor,
        # doc/fault-model.md "Shard supervision plane"): liveness,
        # hot resurrection, degraded-mode bookkeeping.
        from . import supervisor as supervisor_mod

        self.supervisor = supervisor_mod.ShardSupervisor(self)
        # shardDown fast-WAIT cache (ISSUE 18 satellite: PR-17 degraded
        # verdicts fed through the PR-12 negative-cache idea): pod uid ->
        # (shard, shardEpoch, reason). While the owning shard stays down
        # at the same epoch, a pod's re-filter storm is answered by one
        # lock-free dict probe + epoch compare instead of a decision-
        # journal write per re-filter; resurrection's epoch bump
        # self-invalidates every entry. Routed verdicts only — a sweep
        # WAIT also depends on the OTHER shards' capacity, which the
        # (shard, epoch) vector does not cover.
        self._down_wait_cache: Dict[str, Tuple[int, int, str]] = {}
        self._shard_down_fast_waits = 0
        # Control-plane weather plane (doc/fault-model.md): every
        # shard's durable write is brokered through the PARENT's kube
        # client, so the outage detector and the write-behind intent
        # journal live here — __main__'s RetryingKubeClient swap-in
        # inherits both via its scheduler backref (kube.py).
        from . import weather as weather_mod

        self.weather_vane = weather_mod.WeatherVane(
            window=getattr(config, "weather_window", 32),
            blackout_after=getattr(config, "weather_blackout_after", 8),
            clear_after=getattr(config, "weather_clear_after", 3),
        )
        self.intent_journal = weather_mod.IntentJournal(
            capacity=getattr(config, "intent_journal_capacity", 512)
        )

    def _spawn_backend(self, sid: int, owned: Tuple[str, ...]):
        """Build one shard backend (both transports) — used at boot and
        by the supervisor's resurrection path, which must produce a
        backend bit-identical in construction to the boot one."""
        if self.transport == "local":
            server = ShardServer(
                self.config, sid, owned,
                _ShardScopedKubeClient(self, sid),
                auto_admit=self.auto_admit,
                plan=self._plan,
            )
            return LocalShardBackend(server)
        return ProcShardBackend(
            self.config, sid, owned,
            self._make_kube_handler(sid),
            self.auto_admit,
            self._plan,
        )

    # -- supervised backend access (degraded mode) -------------------- #

    def _shard_call(self, sid: int, method: str, *args):
        """Backend call through the supervision plane: a shard already
        known to be down/resurrecting fails fast (no dead-pipe churn),
        and a FRESH worker failure is reported to the supervisor before
        the retriable ShardWorkerError propagates to the verb's
        degraded-mode handler."""
        if not self.supervisor.is_up(sid):
            raise ShardWorkerError(
                f"shard {sid} is {self.supervisor.status(sid)} "
                f"({method})",
                shard_id=sid, method=method, cause="down",
            )
        try:
            return self.shards[sid].call(method, *args)
        except ShardWorkerError as e:
            self.supervisor.note_failure(sid, e, method)
            raise

    def _try_shard_call(self, sid: int, method: str, *args,
                        default=None):
        """Aggregation-path call: a failed shard contributes ``default``
        instead of throwing — inspect/metrics reads must answer with
        explicit attribution (``shardsDown``), never 500."""
        try:
            return self._shard_call(sid, method, *args)
        except ShardWorkerError:
            return default

    def _degraded_wait(self, sid: int, pod_key: str,
                       pod_uid: str, cacheable: bool = True) -> str:
        """Account + journal one degraded-mode WAIT: the pod's owning
        shard is under supervision, so the verdict is WAIT with a
        ``shardDown`` rejection certificate (PR-12 shape: gate + the
        version vector the verdict read — here the shard epoch, which
        the resurrection bumps, so any cached certificate comparison
        fails the moment the shard is back)."""
        from . import decisions as decisions_mod

        self.supervisor.note_degraded_wait(sid)
        status = self.supervisor.status(sid)
        reason = (
            f"shard {sid} is {status} (worker under supervision; "
            "retriable)"
        )
        if cacheable:
            if len(self._down_wait_cache) > 16384:
                self._down_wait_cache.clear()
            self._down_wait_cache[pod_uid] = (
                sid, self.supervisor.epoch(sid), reason
            )
        try:
            rec = self.decisions.begin(pod_key, pod_uid, "filter")
            rec.verdict_wait(reason, certificate={
                "gate": decisions_mod.GATE_SHARD_DOWN,
                "vector": {
                    "shard": sid,
                    "shardEpoch": self.supervisor.epoch(sid),
                },
            })
            self.decisions.commit(rec)
        except Exception:  # noqa: BLE001 — diagnostics must never raise
            common.log.exception("degraded-wait journaling failed")
        return reason

    # -- kube brokering (parent side) -------------------------------- #

    def _make_kube_handler(self, shard_id: int):
        def handle(method: str, args: tuple):
            if method == "persist_scheduler_state":
                return self.store.persist_ledger(shard_id, args[0])
            if method == "load_scheduler_state":
                return self.store.load_ledger(shard_id)
            if method == "persist_snapshot":
                return self.store.persist_snapshot(shard_id, args[0])
            if method == "load_snapshot":
                return self.store.load_snapshot(shard_id)
            return self._handle_kube(method, args)
        return handle

    def _handle_kube(self, method: str, args: tuple):
        """Cluster writes from any shard, behind the frontend's
        leadership fence (the shards themselves are always-leader; HA is
        a parent concern — one lease for the whole shard group)."""
        if not self.is_leader():
            # A DEFINITELY superseded frontend (another holder observed
            # on the Lease, not just a local-expiry blackout) must never
            # drain its journaled intents — the new leader owns the
            # durable state now (same fence as the in-process
            # framework._flush_side_effects).
            if self._definitely_superseded():
                self.intent_journal.discard_all()
            if method == "bind_pod":
                self._deposed_bind_refused += 1
                raise api.WebServerError(
                    503,
                    "not the leader: bind refused (lease lost or "
                    "standby); the active leader will re-schedule "
                    "this pod",
                )
            # Advisory writes (annotation clears, evictions) from a
            # deposed frontend are dropped, mirroring the in-process
            # deposed flush-drop.
            if not self._deposed_drop_logged:
                self._deposed_drop_logged = True
                common.log.warning(
                    "deposed: dropping shard-issued advisory kube "
                    "write %s", method,
                )
            return None
        self._deposed_drop_logged = False
        result = getattr(self.kube_client, method)(*args)
        # Weather plane: a successful leader-fenced write is the healed
        # signal — give the intent journal a drain opportunity (no-op in
        # one dict-len check when the journal is empty).
        drain = getattr(self.kube_client, "maybe_drain", None)
        if drain is not None:
            try:
                drain()
            except Exception as e:  # noqa: BLE001
                common.log.warning("intent journal drain failed: %s", e)
        return result

    # -- routing ------------------------------------------------------ #

    def _route(self, pod: Pod) -> Optional[int]:
        """Owning shard id, or None when the pod cannot be narrowed to
        one shard (the sweep/global path)."""
        cache_key = (
            pod.annotations.get(
                constants.ANNOTATION_POD_SCHEDULING_SPEC, ""
            ),
            pod.node_name,
        )
        cached = self._route_cache.get(cache_key)
        if cached is not None:
            sid, gname = cached
            with self._maps_lock:
                pinned = self._group_shard.get(gname) if gname else None
                known = self._uid_shard.get(pod.uid)
            if pinned is not None:
                return pinned
            return sid if sid is not None else known
        try:
            spec = extract_pod_scheduling_spec(pod)
        except api.WebServerError:
            spec = None
        gname = (
            spec.affinity_group.name
            if spec is not None and spec.affinity_group is not None
            else None
        )
        with self._maps_lock:
            pinned = self._group_shard.get(gname) if gname else None
            known = self._uid_shard.get(pod.uid)
        chains = self.routing.pod_chains(pod, spec)
        sid: Optional[int] = None
        if chains is not None:
            shard_ids = {
                self._shard_of_chain[c]
                for c in chains
                if c in self._shard_of_chain
            }
            if len(shard_ids) == 1:
                sid = next(iter(shard_ids))
        if spec is not None:
            # Cache only chain-derived verdicts (pure config functions);
            # undecodable specs must keep raising inside the shard.
            if len(self._route_cache) > 16384:
                self._route_cache.clear()
            self._route_cache[cache_key] = (sid, gname)
        if pinned is not None:
            return pinned
        return sid if sid is not None else known

    def _note_routed(self, pod: Pod, shard_id: int) -> None:
        cached = self._route_cache.get((
            pod.annotations.get(
                constants.ANNOTATION_POD_SCHEDULING_SPEC, ""
            ),
            pod.node_name,
        ))
        if cached is not None:
            gname = cached[1]
        else:
            try:
                gname = extract_pod_scheduling_spec(
                    pod
                ).affinity_group.name
            except api.WebServerError:
                gname = None
        with self._maps_lock:
            self._uid_shard[pod.uid] = shard_id
            if gname:
                self._group_shard[gname] = shard_id

    def _forget_pod(self, pod: Pod, meta: Optional[Dict]) -> None:
        with self._maps_lock:
            self._uid_shard.pop(pod.uid, None)
            if meta and meta.get("group") and not meta.get("groupLive"):
                self._group_shard.pop(meta["group"], None)

    # -- extender verbs ----------------------------------------------- #

    def filter_routine(self, args: ei.ExtenderArgs) -> ei.ExtenderFilterResult:
        pod = args.pod
        # Causal cross-shard tracing: the frontend's (sampled) trace id
        # travels over the pipe protocol as the worker trace's parent, so
        # the merged /v1/inspect/traces stitches worker spans under the
        # frontend span instead of interleaving unrelated rings.
        tr = self.tracer.trace("filter", pod=pod.key)
        parent = tr.trace_id if tr else None
        result: Optional[ei.ExtenderFilterResult] = None
        try:
            result = self._filter_routine_traced(args, tr, parent)
            return result
        finally:
            rec = self.recorder
            if rec is not None:
                try:
                    self._record_frontend_filter(
                        rec, pod, args.node_names, result
                    )
                except Exception:  # noqa: BLE001
                    common.log.exception("flight-recorder hook failed")

    def _filter_routine_traced(
        self, args: ei.ExtenderArgs, tr, parent
    ) -> ei.ExtenderFilterResult:
        pod = args.pod
        hit = self._down_wait_cache.get(pod.uid)
        if hit is not None:
            dsid, depoch, dreason = hit
            if (not self.supervisor.is_up(dsid)
                    and self.supervisor.epoch(dsid) == depoch):
                # Fast degraded WAIT: the owning shard is still down at
                # the epoch the cached verdict read — same answer, no
                # journal write, no supervisor accounting churn.
                self._shard_down_fast_waits += 1
                tr.finish(
                    outcome="wait", shard=dsid, degraded=True,
                    cached=True,
                )
                return ei.ExtenderFilterResult(
                    failed_nodes={constants.COMPONENT_NAME: dreason}
                )
            self._down_wait_cache.pop(pod.uid, None)
        sid = self._route(pod)
        if sid is not None:
            try:
                with tr.span("shardCall", shard=sid):
                    result = self._shard_call(
                        sid, "filter_routine", args, None, parent
                    )
            except ShardWorkerError:
                # Degraded mode: the owning shard is under supervision —
                # WAIT (with the shardDown certificate), never a 500.
                reason = self._degraded_wait(sid, pod.key, pod.uid)
                tr.finish(outcome="wait", shard=sid, degraded=True)
                return ei.ExtenderFilterResult(
                    failed_nodes={constants.COMPONENT_NAME: reason}
                )
            self._note_routed(pod, sid)
            tr.finish(outcome=_frontend_outcome(result), shard=sid)
            return result
        # Sweep (cross-family untyped pod): leaf-type-granular, in the
        # global sorted leaf-type order — each chunk is a consecutive
        # same-shard run probed with the scan restricted to exactly its
        # leaf types, so the first non-wait outcome is the one the
        # single process's any-leaf-type scan finds (module docstring).
        result = None
        skipped: Optional[int] = None
        for sid, leaf_types in self._sweep_chunks:
            try:
                with tr.span("shardCall", shard=sid, sweep=True):
                    result = self._shard_call(
                        sid, "filter_sweep", args, leaf_types, parent
                    )
            except ShardWorkerError:
                # A down chunk cannot veto the sweep: the other shards
                # may still place the pod. If none does, the verdict
                # degrades to the shardDown WAIT below (the skipped
                # shard might have said yes).
                skipped = sid
                result = None
                continue
            if result.node_names or (
                result.failed_nodes
                and set(result.failed_nodes) != {constants.COMPONENT_NAME}
            ):
                self._note_routed(pod, sid)
                tr.finish(outcome=_frontend_outcome(result), shard=sid)
                return result
        if skipped is not None:
            reason = self._degraded_wait(
                skipped, pod.key, pod.uid, cacheable=False
            )
            tr.finish(outcome="wait", sweep=True, degraded=True)
            return ei.ExtenderFilterResult(
                failed_nodes={constants.COMPONENT_NAME: reason}
            )
        tr.finish(outcome="wait", sweep=True)
        return result if result is not None else ei.ExtenderFilterResult(
            failed_nodes={
                constants.COMPONENT_NAME: "no shard can serve this pod"
            }
        )

    def _record_frontend_filter(self, rec, pod, node_names, result):
        """Frontend (pre-routing) capture: one stream covers all shards.
        (pod, node) granularity — chip isolation lives shard-side."""
        rec.record_filter(
            pod, node_names, _frontend_outcome(result),
            node=(
                result.node_names[0]
                if result is not None and result.node_names
                else ""
            ),
        )

    def filter_raw(self, body: bytes) -> bytes:
        """Raw-bytes filter: route from a JSON peek, forward the body
        verbatim, return the worker's encoded reply verbatim. The
        webserver prefers this entry when present: the parent never
        builds the dataclasses or re-encodes — its per-call cost is one
        C-level json.loads of the body (~50us at 432 hosts) plus a
        route-cache hit, with the decoded node list reused for the
        filter_fast memo key. A sampled frontend trace id rides the pipe
        as the worker trace's parent; the (frontend-level) flight
        recorder classifies the encoded reply without re-decoding more
        than the outcome fields."""
        wire_body = wire_mod.is_wire(body)
        in_len = len(body)
        if wire_body:
            # Binary extender frame (hack/sim_server.py): the envelope
            # is a frame whose payload is the args dict; the reply goes
            # back as a frame wrapping the encoded JSON reply bytes. A
            # WireVersionError propagates — the webserver answers 415
            # and the client re-sends legacy JSON (lossless fallback).
            d = wire_mod.loads(body)
            body = None
        else:
            try:
                d = json.loads(body)
            except (ValueError, TypeError) as e:
                return json.dumps(ei.ExtenderFilterResult(
                    error=f"Failed to unmarshal request body: {e}"
                ).to_dict()).encode()
        out_bytes, outcome, node = self._filter_raw_routed(d, body)
        if wire_body:
            out_bytes = wire_mod.dumps(out_bytes)
        # The HTTP envelope codec split: bytes in and out of the
        # frontend (doc/observability.md wireBytesTotal).
        with self._maps_lock:
            self._wire_env_bytes[
                "binary" if wire_body else "json"
            ] += in_len + len(out_bytes)
        rec = self.recorder
        if rec is not None:
            try:
                # Outcome classified from the ALREADY-decoded worker
                # reply inside the routed path, pod memoized from the
                # decoded request — the recorder costs the raw hot path
                # no reply re-decode and no per-call dataclass rebuild.
                rec.record_filter_wire(d, outcome, node=node)
            except Exception:  # noqa: BLE001
                common.log.exception("flight-recorder hook failed")
        return out_bytes

    def _filter_raw_routed(
        self, d: Dict, body: bytes
    ) -> Tuple[bytes, str, str]:
        """Returns (encoded reply, outcome class, bound node or "") —
        the outcome rides along from wherever the reply was already a
        decoded dict, so the recorder never re-parses the bytes."""
        tr = self.tracer.trace("filter")
        parent = tr.trace_id if tr else None
        pod_d = d.get("Pod") or {}
        md = pod_d.get("metadata") or {}
        ann = str((md.get("annotations") or {}).get(
            constants.ANNOTATION_POD_SCHEDULING_SPEC, ""
        ))
        node = str((pod_d.get("spec") or {}).get("nodeName", "") or "")
        uid = str(md.get("uid", "") or "")
        hit = self._down_wait_cache.get(uid)
        if hit is not None:
            dsid, depoch, dreason = hit
            if (not self.supervisor.is_up(dsid)
                    and self.supervisor.epoch(dsid) == depoch):
                self._shard_down_fast_waits += 1
                tr.finish(
                    pod=uid, shard=dsid, degraded=True, cached=True
                )
                return json.dumps(
                    ei.ExtenderFilterResult(failed_nodes={
                        constants.COMPONENT_NAME: dreason
                    }).to_dict()
                ).encode(), "wait", ""
            self._down_wait_cache.pop(uid, None)
        cached = self._route_cache.get((ann, node))
        if cached is not None:
            sid, gname = cached
            with self._maps_lock:
                pinned = self._group_shard.get(gname) if gname else None
                known = self._uid_shard.get(uid)
            if pinned is not None:
                sid = pinned
            elif sid is None:
                sid = known
        else:
            pod = ei.pod_from_k8s(pod_d)
            sid = self._route(pod)
            cached = self._route_cache.get((ann, node)) or (sid, None)
        if sid is not None:
            nodes = [str(n) for n in (d.get("NodeNames") or [])]
            nodes_key = tuple(nodes)
            with self._maps_lock:
                nid = self._nodes_ids.get(nodes_key)
                if nid is None:
                    if len(self._nodes_ids) > 4096:
                        # A forgotten mapping only costs one full
                        # re-send; the delta bases die with the ids.
                        self._nodes_ids.clear()
                        for s in self._nodes_sent:
                            s.clear()
                        self._nodes_acked = [
                            None for _ in self._nodes_acked
                        ]
                    # The set id IS the PR-12 token: hashed once here,
                    # reused as the worker cache key, the delta base
                    # reference, and the wait-cache memo seed.
                    nid = self._nodes_ids[nodes_key] = (
                        len(nodes_key), hash(nodes_key)
                    )
                send_full = nid not in self._nodes_sent[sid]
                payload = nodes if send_full else None
                if send_full and self._wire_on:
                    # Churned set: ship an edit script against a set
                    # this shard already holds instead of the full
                    # O(fleet) list. The (base, new) transition memo is
                    # single-slot because every shard crosses the same
                    # fleet transitions one after another.
                    acked = self._nodes_acked[sid]
                    if acked is not None:
                        base_id, base_key = acked
                        memo = self._delta_memo
                        if (
                            memo is not None
                            and memo[0] is base_key
                            and memo[1] is nodes_key
                        ):
                            delta = memo[2]
                        else:
                            delta = _suggested_delta(
                                base_key, nodes_key, base_id
                            )
                            self._delta_memo = (
                                base_key, nodes_key, delta
                            )
                        if delta is not None:
                            payload = delta
            # The pod dict is JSON-born (decoded straight from the
            # request body), so the wire codec may ship it as one
            # C-speed json blob instead of an element walk.
            pod_w = wire_mod.Json(pod_d) if self._wire_on else pod_d
            try:
                with tr.span("shardCall", shard=sid):
                    out = self._shard_call(
                        sid, "filter_fast", pod_w, nid, payload, parent,
                    )
                    if out.get("__needNodes"):
                        if _is_delta_marker(payload):
                            # Delta base miss/mismatch: the resync path —
                            # counted, then the full list goes out.
                            with self._maps_lock:
                                self._delta_resyncs += 1
                        out = self._shard_call(
                            sid, "filter_fast", pod_w, nid, nodes, parent
                        )
            except ShardWorkerError:
                reason = self._degraded_wait(
                    sid, f"{md.get('namespace', '')}/"
                    f"{md.get('name', '')}", uid,
                )
                tr.finish(pod=uid, shard=sid, degraded=True)
                return json.dumps(
                    ei.ExtenderFilterResult(failed_nodes={
                        constants.COMPONENT_NAME: reason
                    }).to_dict()
                ).encode(), "wait", ""
            with self._maps_lock:
                self._nodes_sent[sid].add(nid)
                self._nodes_acked[sid] = (nid, nodes_key)
                self._uid_shard[uid] = sid
                if cached[1]:
                    self._group_shard[cached[1]] = sid
            tr.finish(pod=uid, shard=sid)
            outcome, bound = _raw_outcome(out)
            return json.dumps(out).encode(), outcome, bound
        # Sweep (cross-family untyped pod): leaf-type-granular chunks in
        # the global sorted leaf-type order, first non-wait outcome wins
        # (identical probe order to the in-process scan).
        out = None
        r = None
        if body is None:
            # Wire-framed request (no JSON envelope to forward): the
            # sweep workers decode JSON, so re-encode once. Rare path —
            # sweeps are cross-family untyped pods only.
            body = json.dumps(d).encode()
        skipped: Optional[int] = None
        for sid, leaf_types in self._sweep_chunks:
            try:
                with tr.span("shardCall", shard=sid, sweep=True):
                    out = self._shard_call(
                        sid, "filter_sweep_raw", body, leaf_types, parent
                    )
            except ShardWorkerError:
                skipped = sid
                out = r = None
                continue
            r = json.loads(out)
            if r.get("NodeNames") or r.get("Error") or (
                r.get("FailedNodes")
                and set(r["FailedNodes"]) != {constants.COMPONENT_NAME}
            ):
                with self._maps_lock:
                    self._uid_shard[uid] = sid
                    if cached is not None and cached[1]:
                        self._group_shard[cached[1]] = sid
                tr.finish(pod=uid, shard=sid, sweep=True)
                outcome, bound = _raw_outcome(r)
                return out, outcome, bound
        if skipped is not None:
            reason = self._degraded_wait(
                skipped, f"{md.get('namespace', '')}/"
                f"{md.get('name', '')}", uid, cacheable=False,
            )
            tr.finish(pod=uid, sweep=True, degraded=True)
            return json.dumps(
                ei.ExtenderFilterResult(failed_nodes={
                    constants.COMPONENT_NAME: reason
                }).to_dict()
            ).encode(), "wait", ""
        tr.finish(pod=uid, sweep=True)
        if out is not None:
            outcome, bound = _raw_outcome(r)
            return out, outcome, bound
        return json.dumps(
            ei.ExtenderFilterResult(failed_nodes={
                constants.COMPONENT_NAME: "no shard can serve this pod"
            }).to_dict()
        ).encode(), "wait", ""

    def preempt_routine(
        self, args: ei.ExtenderPreemptionArgs
    ) -> ei.ExtenderPreemptionResult:
        pod = args.pod
        tr = self.tracer.trace("preempt", pod=pod.key)
        parent = tr.trace_id if tr else None
        result: Optional[ei.ExtenderPreemptionResult] = None
        try:
            sid = self._route(pod)
            if sid is not None:
                try:
                    with tr.span("shardCall", shard=sid):
                        result = self._shard_call(
                            sid, "preempt_routine", args, parent
                        )
                except ShardWorkerError:
                    # Degraded: no victims named (an empty preemption
                    # result means "cannot preempt right now" to the
                    # default scheduler — retriable, never a 500).
                    self.supervisor.note_degraded_wait(sid)
                    tr.finish(shard=sid, degraded=True)
                    result = ei.ExtenderPreemptionResult()
                    return result
                self._note_routed(pod, sid)
                tr.finish(shard=sid)
                return result
            for sid in range(len(self.shards)):
                try:
                    with tr.span("shardCall", shard=sid):
                        result = self._shard_call(
                            sid, "preempt_routine", args, parent
                        )
                except ShardWorkerError:
                    self.supervisor.note_degraded_wait(sid)
                    result = None
                    continue
                if result.node_name_to_meta_victims:
                    self._note_routed(pod, sid)
                    tr.finish(shard=sid)
                    return result
            tr.finish()
            return result if result is not None else (
                ei.ExtenderPreemptionResult()
            )
        finally:
            rec = self.recorder
            if rec is not None:
                try:
                    recorder_pkg.record_preempt_result(
                        rec, pod, args, result
                    )
                except Exception:  # noqa: BLE001
                    common.log.exception("flight-recorder hook failed")

    def bind_routine(
        self, args: ei.ExtenderBindingArgs
    ) -> ei.ExtenderBindingResult:
        tr = self.tracer.trace("bind", pod=args.pod_uid)
        parent = tr.trace_id if tr else None
        ok = False
        try:
            result = self._bind_routine_routed(args, tr, parent)
            ok = True
            return result
        finally:
            rec = self.recorder
            if rec is not None:
                try:
                    rec.record_bind(
                        args.pod_name, args.pod_namespace, args.pod_uid,
                        args.node, ok,
                    )
                except Exception:  # noqa: BLE001
                    common.log.exception("flight-recorder hook failed")

    def _bind_routine_routed(
        self, args: ei.ExtenderBindingArgs, tr, parent
    ) -> ei.ExtenderBindingResult:
        with self._maps_lock:
            sid = self._uid_shard.get(args.pod_uid)
        if sid is not None:
            try:
                with tr.span("shardCall", shard=sid):
                    result = self._shard_call(
                        sid, "bind_routine", args, parent
                    )
            except ShardWorkerError:
                # Degraded: refuse the bind RETRIABLY (503, the deposed-
                # leader shape) — the default scheduler re-runs the
                # cycle, and the resurrected shard recovers the pod's
                # admission from its annotations. Never a 500.
                self.supervisor.note_degraded_wait(sid)
                tr.finish(shard=sid, outcome="error", degraded=True)
                raise api.WebServerError(
                    503,
                    f"shard {sid} is {self.supervisor.status(sid)}: "
                    "bind refused; the scheduler will retry once the "
                    "shard is resurrected",
                )
            tr.finish(shard=sid)
            return result
        # Unknown uid (e.g. a bind racing recovery): ask each shard; the
        # non-owners reject with the admission protocol error.
        last: Optional[api.WebServerError] = None
        for s in range(len(self.shards)):
            try:
                with tr.span("shardCall", shard=s):
                    result = self._shard_call(s, "bind_routine", args, parent)
                tr.finish(shard=s)
                return result
            except api.WebServerError as e:
                last = e
            except ShardWorkerError:
                self.supervisor.note_degraded_wait(s)
                if last is None:
                    last = api.WebServerError(
                        503,
                        f"shard {s} is "
                        f"{self.supervisor.status(s)}: bind refused; "
                        "retry after resurrection",
                    )
        tr.finish(outcome="error")
        raise last if last is not None else api.bad_request(
            "Pod does not exist, completed or has not been informed to "
            "the scheduler"
        )

    def handle_terminal_bind_failure(self, binding_pod: Pod) -> None:
        sid = self._route(binding_pod)
        targets = [sid] if sid is not None else range(len(self.shards))
        for s in targets:
            # A down shard's recovery replays the pod's annotations and
            # re-derives the failure handling; skipping is safe.
            self._try_shard_call(
                s, "handle_terminal_bind_failure", binding_pod
            )

    # -- pod lifecycle events ----------------------------------------- #

    def _record(self, method: str, *args) -> None:
        """Frontend flight-recorder capture for the informer verbs (the
        extender verbs record inline where the outcome is known). Nested
        verbs (update_pod's delete+add degrade) are not re-recorded —
        the outer event replays them through the same degrade path."""
        rec = self.recorder
        if rec is None or getattr(self._rec_nested, "d", 0):
            return
        try:
            getattr(rec, method)(*args)
        except Exception:  # noqa: BLE001 — recording must never raise
            common.log.exception("flight-recorder hook failed")

    def add_pod(self, pod: Pod) -> None:
        if self._informer_capture is not None:
            # Informer boot replay: finish_recovery's authoritative pod
            # list carries this pod into the fan-out.
            return
        self._record("record_pod_event", "pod_add", pod)
        self.supervisor.note_pod(pod)
        sid = self._route(pod)
        if sid is not None:
            # A down owner misses nothing: the supervisor mirror carries
            # this pod into the resurrection's recovery slice.
            self._try_shard_call(sid, "add_pod", pod)
            self._note_routed(pod, sid)
            return
        # Unroutable (untyped cross-family, or undecodable spec): every
        # shard admits it — the sweep's later filter finds it wherever it
        # runs, exactly as the single process's one status map would.
        for s in range(len(self.shards)):
            self._try_shard_call(s, "add_pod", pod)

    def update_pod(self, old: Pod, new: Pod) -> None:
        self._record("record_pod_update", old, new)
        sid_old, sid_new = self._route(old), self._route(new)
        if sid_old == sid_new and sid_new is not None:
            if old.uid != new.uid:
                self.supervisor.note_pod_delete(old.uid)
            self.supervisor.note_pod(new)
            self._try_shard_call(sid_new, "update_pod", old, new)
            self._note_routed(new, sid_new)
            return
        if sid_old is None and sid_new is None:
            if old.uid != new.uid:
                self.supervisor.note_pod_delete(old.uid)
            self.supervisor.note_pod(new)
            for s in range(len(self.shards)):
                self._try_shard_call(s, "update_pod", old, new)
            return
        # Routing moved (uid change across SKUs, or one side unroutable):
        # degrade to delete+add, the framework's own fallback shape (the
        # nested pair is NOT re-recorded — the update event replays it).
        self._rec_nested.d = getattr(self._rec_nested, "d", 0) + 1
        try:
            self.delete_pod(old)
            self.add_pod(new)
        finally:
            self._rec_nested.d -= 1

    def delete_pod(self, pod: Pod) -> None:
        self._record("record_pod_event", "pod_delete", pod)
        self.supervisor.note_pod_delete(pod.uid)
        sid = self._route(pod)
        if sid is not None:
            # A down owner's delete is mirror-only: the resurrection's
            # recovery slice simply no longer contains the pod.
            meta = self._try_shard_call(sid, "delete_pod_meta", pod)
            self._forget_pod(pod, meta)
            return
        # Broadcast delete: the pin drops only when NO shard still holds
        # the group (same any()-liveness rule as delete_pods).
        metas = [
            m for m in (
                self._try_shard_call(s, "delete_pod_meta", pod)
                for s in range(len(self.shards))
            ) if m is not None
        ]
        self._forget_pod(pod, {
            "group": metas[0].get("group") if metas else None,
            "groupLive": any(m.get("groupLive") for m in metas),
        })

    def delete_pods(self, pods: List[Pod]) -> None:
        """Bulk delete: grouped per owning shard, one RPC per shard. An
        unroutable pod broadcasts, and its group pin is dropped only when
        NO shard still holds the group (any shard's live group keeps the
        pin — judging liveness by one arbitrary shard could unpin a gang
        that is still placed elsewhere)."""
        for pod in pods:
            self._record("record_pod_event", "pod_delete", pod)
            self.supervisor.note_pod_delete(pod.uid)
        per_shard: Dict[Optional[int], List[Pod]] = {}
        for pod in pods:
            per_shard.setdefault(self._route(pod), []).append(pod)
        for sid, group in per_shard.items():
            targets = (
                [sid] if sid is not None else range(len(self.shards))
            )
            all_metas = [
                m for m in (
                    self._try_shard_call(s, "delete_pods_meta", group)
                    for s in targets
                ) if m is not None
            ]
            for i, pod in enumerate(group):
                per_pod = [m[i] for m in all_metas]
                self._forget_pod(pod, {
                    "group": per_pod[0].get("group") if per_pod else None,
                    "groupLive": any(
                        m.get("groupLive") for m in per_pod
                    ),
                })

    # -- shadow what-if plane (aggregated) ----------------------------- #

    def whatif_routine(self, payload: Dict) -> Dict:
        """POST /v1/inspect/whatif across the shard fleet. Each shard
        forks its OWN core (its owned chains are the only authoritative
        state it holds) and forecasts its own slice of the waiting
        queue; the frontend merges. A gang a sweep registered in several
        shards keeps its BEST forecast — earliest ETA, blocked sorts
        last — because the gang schedules the moment ANY shard can place
        it (placement-found-iff, the sweep's own contract). Known
        artifact (doc/hot-path.md "Shadow what-if plane" honest nulls):
        such a cross-family gang occupies EVERY probed shard's fork, so
        other gangs sharing a non-winning shard see phantom occupancy
        and forecast pessimistic — safe-direction skew (promises err
        late, never early). A single-spec forecast routes by its leaf
        type like a filter; a capacity plan fans out over per-shard
        trace slices and sums."""
        if not isinstance(payload, dict):
            raise api.bad_request("whatif payload must be a JSON object")
        if payload.get("spec") is not None:
            if not isinstance(payload["spec"], dict):
                # Mirror the single-process 400 (a bare string spec must
                # not 500 out of the leafType peek below).
                raise api.bad_request(
                    "whatif spec must be an object with "
                    "name/vc/leafType/pods/chips/priority"
                )
            leaf = str(payload["spec"].get("leafType") or "")
            chains = self.routing.leaf_chains.get(leaf)
            sid = (
                self._shard_of_chain.get(chains[0]) if chains else None
            )
            if sid is None:
                raise api.bad_request(
                    f"whatif spec names leaf cell type {leaf!r} which "
                    "the cluster does not have"
                )
            try:
                return self._shard_call(sid, "whatif_routine", payload)
            except ShardWorkerError:
                raise api.WebServerError(
                    503,
                    f"shard {sid} is {self.supervisor.status(sid)}: "
                    "what-if forecast unavailable until it is "
                    "resurrected",
                )
        if payload.get("capacityTrace") is not None:
            return self._whatif_capacity(payload)
        # Queue mode: shards must NOT stamp their LOCAL verdicts — a
        # sweep-registered gang's shard-local forecast (blocked on the
        # families that shard owns) can contradict the merged answer.
        # The frontend stamps the MERGED forecast into every shard's
        # journal afterwards.
        fan_payload = dict(payload)
        stamp = bool(fan_payload.get("stamp", True))
        fan_payload["stamp"] = False
        replies = self._whatif_fan_out("whatif_routine", fan_payload)
        # Degraded mode: a down shard contributes no forecasts — its
        # gangs are WAITing on shardDown anyway, and the merged answer
        # attributes the gap instead of 500ing the whole forecast.
        live = [r for r in replies if r is not None]
        shards_down = [
            sid for sid, r in enumerate(replies) if r is None
        ]
        merged: Dict[str, Dict] = {}
        order: List[str] = []

        def better(a: Dict, b: Dict) -> bool:
            ka = (a["predictedWaitS"] is None, a["predictedWaitS"] or 0.0)
            kb = (b["predictedWaitS"] is None, b["predictedWaitS"] or 0.0)
            return ka < kb

        for reply in live:
            for f in reply.get("forecasts") or []:
                cur = merged.get(f["gang"])
                if cur is None:
                    merged[f["gang"]] = f
                    order.append(f["gang"])
                elif better(f, cur):
                    merged[f["gang"]] = f
        if stamp and merged:
            # The horizon the stamps are conditioned on: every shard
            # already derived (and validated) it — read it back from a
            # reply's meta instead of re-deriving a second copy here.
            duration = next(
                (
                    m["confidenceHorizonS"]
                    for m in (r.get("meta") or {} for r in live)
                    if "confidenceHorizonS" in m
                ),
                0.0,
            )
            items = [(g, merged[g]["predictedWaitS"]) for g in order]
            for sid in range(len(self.shards)):
                self._try_shard_call(sid, "whatif_stamp", items, duration)
        meta: Dict = {
            "shards": len(self.shards),
            "perShard": [
                r.get("meta") if r is not None else None for r in replies
            ],
        }
        if shards_down:
            meta["shardsDown"] = shards_down
        return {
            "mode": "queue",
            "forecasts": [merged[g] for g in order],
            "meta": meta,
        }

    def _whatif_fan_out(
        self, method: str, payloads
    ) -> List[Dict]:
        """Per-shard whatif calls, in parallel for process backends
        (each is a full fork build + horizon replay — wall time must be
        the max of the shards, not the sum; the recover() fan-out
        pattern). ``payloads`` is one shared payload dict, or a list
        with one payload per shard. A down shard's slot stays None
        (degraded mode — callers attribute the gap)."""
        per_shard = (
            payloads
            if isinstance(payloads, list)
            else [payloads] * len(self.shards)
        )
        results: List[Optional[Dict]] = [None] * len(self.shards)
        errors: List[BaseException] = []

        def run(sid: int) -> None:
            try:
                results[sid] = self._shard_call(
                    sid, method, per_shard[sid]
                )
            except ShardWorkerError:
                pass  # degraded: slot stays None
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        if self.transport == "proc" and len(self.shards) > 1:
            threads = [
                threading.Thread(target=run, args=(sid,))
                for sid in range(len(self.shards))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        else:
            for sid in range(len(self.shards)):
                run(sid)
        if errors:
            raise errors[0]
        return results  # type: ignore[return-value]

    def _whatif_capacity(self, payload: Dict) -> Dict:
        """Capacity planning across shards: each shard's fork holds only
        its owned chains' state, so the trace is SLICED — every submit
        goes to the one shard owning its leaf type (replaying the full
        trace everywhere would count each foreign-SKU gang as unbound N-1
        times and tell operators to buy capacity they have). Fault/other
        events broadcast, like live node events do. Per-shard risks then
        sum correctly because the submits partition."""
        trace = payload["capacityTrace"] or {}
        slices: List[List[Dict]] = [[] for _ in self.shards]
        for ev in trace.get("events") or []:
            if ev.get("kind") == "submit":
                leaf = str((ev.get("gang") or {}).get("leafType") or "")
                chains = self.routing.leaf_chains.get(leaf)
                sid = (
                    self._shard_of_chain.get(chains[0])
                    if chains
                    else None
                )
                slices[sid if sid is not None else 0].append(ev)
            else:
                for s in slices:
                    s.append(ev)
        per_shard = []
        for sid in range(len(self.shards)):
            sub = dict(payload)
            sub["capacityTrace"] = dict(trace, events=slices[sid])
            per_shard.append(sub)
        replies = self._whatif_fan_out("whatif_routine", per_shard)
        live = [r for r in replies if r is not None]
        shards_down = [
            sid for sid, r in enumerate(replies) if r is None
        ]
        sub_g = sum(
            r["counts"]["submittedGuaranteed"] for r in live
        )
        bound_g = sum(r["counts"]["boundGuaranteed"] for r in live)
        result = {
            "mode": "capacity",
            "perShard": replies,
            "sloRisk": {
                "unboundGuaranteed": sub_g - bound_g,
                "quotaSatisfaction": (
                    round(bound_g / sub_g, 4) if sub_g else 1.0
                ),
                "waitingAtEnd": sum(
                    r["sloRisk"]["waitingAtEnd"] for r in live
                ),
                "p99OverSlo": any(
                    r["sloRisk"]["p99OverSlo"] for r in live
                ),
            },
        }
        if shards_down:
            # A down shard's submit slice went unforecast — say so
            # rather than report a silently-partial plan.
            result["shardsDown"] = shards_down
        return result

    # -- node / health events (global mode) --------------------------- #

    def _node_targets(self, node_name: str) -> List[int]:
        chains = self.routing.node_chains.get(node_name)
        if not chains:
            # Unknown-to-config node: every shard caches it for bind
            # validation, none gains capacity.
            return list(range(len(self.shards)))
        return sorted({
            self._shard_of_chain[c]
            for c in chains
            if c in self._shard_of_chain
        })

    def _commit_phase(self, backend, op_id: int):
        """Phase 2 of the broadcast — a seam the chaos sensitivity
        meta-test no-ops to prove the harness notices a torn broadcast."""
        return backend.call("op_commit", op_id)

    def _broadcast(self, method: str, args: tuple,
                   targets: Optional[List[int]] = None) -> List:
        """Two-phase broadcast: stage everywhere, then commit in
        ascending shard order. A single-target broadcast degenerates to
        a direct call (no second phase to tear).

        Degraded mode: shards the supervisor holds non-up are skipped
        up front, and a shard that DIES mid-broadcast is dropped from
        the round instead of failing it — every verb broadcast here
        (node events, health/clock ticks) is exactly what the
        supervisor's mirror journal replays into the resurrected
        worker, so the skipped shard converges on the same state."""
        ids = (
            list(range(len(self.shards))) if targets is None else targets
        )
        ids = [sid for sid in ids if self.supervisor.is_up(sid)]
        if not ids:
            return []
        if len(ids) == 1:
            try:
                return [self._shard_call(ids[0], method, *args)]
            except ShardWorkerError:
                return [None]
        with self._op_lock:
            op_id = next(self._op_seq)
        staged: List[int] = []
        try:
            for sid in ids:
                try:
                    self._shard_call(sid, "op_stage", op_id, method, args)
                except ShardWorkerError:
                    continue  # died mid-round: journal replay covers it
                staged.append(sid)
        except BaseException:
            for sid in staged:
                try:
                    self.shards[sid].call("op_abort", op_id)
                except Exception:  # noqa: BLE001
                    pass
            raise
        # Phase 2: every staged shard gets its commit even when an
        # earlier one fails (op_commit pops the staged entry before
        # applying, so the failed shard itself holds nothing) — a
        # commit-phase error must not leave later shards staged-forever
        # while earlier shards already applied. The first error re-raises
        # after the sweep; a worker DEATH does not (retriable — the
        # resurrection replay re-delivers the event).
        results: List = []
        first_err: Optional[BaseException] = None
        for sid in sorted(ids):
            if sid not in staged:
                results.append(None)
                continue
            try:
                results.append(self._commit_phase(self.shards[sid], op_id))
            except ShardWorkerError as e:
                self.supervisor.note_failure(sid, e, method)
                results.append(None)
            except BaseException as e:  # noqa: BLE001
                if first_err is None:
                    first_err = e
                results.append(None)
        if first_err is not None:
            raise first_err
        return results

    def add_node(self, node: Node) -> None:
        if self._informer_capture is not None:
            self._informer_capture["nodes"].append(node)
            return
        self._record("record_node_event", "node_add", node)
        self.supervisor.note_node(node)
        self._broadcast("add_node", (node,), self._node_targets(node.name))

    def add_nodes(self, nodes: List[Node]) -> None:
        """Batched boot adds (the informer's initial list). During the
        boot capture they buffer like add_node; live, they group per
        shard-target set so each target shard sees one batched call."""
        if self._informer_capture is not None:
            self._informer_capture["nodes"].extend(nodes)
            return
        for node in nodes:
            self._record("record_node_event", "node_add", node)
            self.supervisor.note_node(node)
        per_targets: Dict[Tuple[int, ...], List[Node]] = {}
        for node in nodes:
            key = tuple(self._node_targets(node.name))
            per_targets.setdefault(key, []).append(node)
        for targets, group in per_targets.items():
            self._broadcast("add_nodes", (group,), list(targets))

    def update_node(self, old: Node, new: Node) -> None:
        if self._informer_capture is not None:
            self._informer_capture["nodes"].append(new)
            return
        self._record("record_node_event", "node_state", new)
        self.supervisor.note_node(new)
        self._broadcast(
            "update_node", (old, new), self._node_targets(new.name)
        )

    def delete_node(self, node: Node) -> None:
        self._record("record_node_event", "node_delete", node)
        self.supervisor.note_node_delete(node.name)
        self._broadcast(
            "delete_node", (node,), self._node_targets(node.name)
        )

    def health_tick(self) -> None:
        self._record("record_marker", "health_tick")
        self.supervisor.note_tick()
        self._broadcast("health_tick", ())

    def settle_health_now(self) -> None:
        self._record("record_marker", "settle_health")
        self._broadcast("settle_health_now", ())

    def settle_health_wall(self) -> None:
        self._record("record_marker", "settle_health_wall")
        self._broadcast("settle_health_wall", ())

    def health_pending_count(self) -> int:
        return sum(
            self._try_shard_call(sid, "health_pending_count", default=0)
            for sid in range(len(self.shards))
        )

    # -- recovery (fan-out) ------------------------------------------- #

    def note_watermark(self, watermark) -> None:
        self._watermark = watermark

    def recover(self, nodes: Iterable[Node], pods: Iterable[Pod],
                min_watermark=None) -> None:
        """Partition the cluster state by owning shard and fan the
        replay out: every shard restores its own ledger/snapshot slot
        and delta-replays its own chains — in parallel for process
        backends (the recovery-blackout win scales with shards)."""
        # Full recovery supersedes per-shard supervision: authoritative
        # state is about to replay into every backend, so force-respawn
        # anything dead/down and reset the breakers first.
        self.supervisor.ensure_all_up()
        node_list, pod_list = list(nodes), list(pods)
        node_slices: List[List[Node]] = [[] for _ in self.shards]
        for node in node_list:
            for sid in self._node_targets(node.name):
                node_slices[sid].append(node)
        pod_slices: List[List[Pod]] = [[] for _ in self.shards]
        for pod in pod_list:
            sid = self._route_recovery_pod(pod)
            if sid is None:
                for s in pod_slices:
                    s.append(pod)
            else:
                pod_slices[sid].append(pod)

        results: List[Optional[Dict]] = [None] * len(self.shards)
        errors: List[BaseException] = []

        def run(sid: int) -> None:
            try:
                results[sid] = self.shards[sid].call(
                    "recover_slice", node_slices[sid], pod_slices[sid],
                    min_watermark,
                )
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        if self.transport == "proc" and len(self.shards) > 1:
            threads = [
                threading.Thread(target=run, args=(sid,))
                for sid in range(len(self.shards))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        else:
            for sid in range(len(self.shards)):
                run(sid)
        if errors:
            raise errors[0]
        with self._maps_lock:
            self._uid_shard.clear()
            self._group_shard.clear()
            for sid, state in enumerate(results):
                if state is None:
                    continue
                for uid in state["uids"]:
                    self._uid_shard[uid] = sid
                for g in state["groups"]:
                    self._group_shard[g] = sid
        self.supervisor.note_recovered(node_list, pod_list)
        self._ready.set()

    def _route_recovery_pod(self, pod: Pod) -> Optional[int]:
        """Recovery routing: a bound pod belongs where its node's chains
        live (exact — its cells are on that node even after a
        reconfiguration moved the node); unbound pods route by spec."""
        if is_bound(pod) and pod.node_name in self.routing.node_chains:
            sids = {
                self._shard_of_chain[c]
                for c in self.routing.node_chains[pod.node_name]
                if c in self._shard_of_chain
            }
            if len(sids) == 1:
                return next(iter(sids))
        return self._route(pod)

    # -- informer-boot surface (kube.InformerLoop.start) --------------- #
    #
    # The informer's boot protocol replays the initial lists through the
    # single-process recovery bracket. The frontend CAPTURES that replay
    # (node events buffer, pod events are covered by finish_recovery's
    # authoritative list) and fans it out through recover() — where each
    # shard loads and validates its own snapshot/ledger partition. The
    # frontend therefore reports "no snapshot" to the informer: partition
    # validation is a per-shard decision, not a frontend-level one.

    def load_valid_snapshot(self, min_watermark=None):
        return None

    def discard_preapplied_state(self) -> None:
        for sid in range(len(self.shards)):
            self._try_shard_call(sid, "discard_preapplied_state")

    def begin_recovery(self, ledger_payload=None,
                       defer_doom_rebuild: bool = False) -> None:
        # The ledger payload is the raw partition envelope; each shard
        # loads its own slot through the partition store during recover().
        self._informer_capture = {"nodes": []}

    def _abort_recovery(self) -> None:
        self._informer_capture = None

    def finish_recovery(self, pods: List[Pod]) -> None:
        capture, self._informer_capture = self._informer_capture, None
        self.recover(
            capture["nodes"] if capture else [], pods, min_watermark=None
        )

    def mark_ready(self) -> None:
        # A down shard is marked ready on resurrection instead
        # (supervisor._recover_shard checks front.is_ready()).
        for sid in range(len(self.shards)):
            self._try_shard_call(sid, "mark_ready")
        self._ready.set()

    def is_ready(self) -> bool:
        return self._ready.is_set()

    def is_leader(self) -> bool:
        lead = self.leadership
        return lead is None or lead.is_leader()

    def _definitely_superseded(self) -> bool:
        """True only when another identity has been OBSERVED holding the
        Lease — the discard fence for the intent journal. A leader that
        merely cannot renew (apiserver unreachable, local expiry) keeps
        its journal for the own-lease warm-resumption path."""
        lead = self.leadership
        if lead is None:
            return False
        holder = str(getattr(lead, "observed_holder", "") or "")
        return bool(holder) and holder != str(
            getattr(lead, "identity", "")
        )

    @property
    def kube_client(self) -> KubeClient:
        return self._kube_client

    @kube_client.setter
    def kube_client(self, client: KubeClient) -> None:
        # __main__ swaps in the RetryingKubeClient after construction;
        # the partition store must write through the same client.
        self._kube_client = client
        if hasattr(self, "store"):
            self.store.kube = client

    def prefetch_snapshot(self, min_watermark=None, apply: bool = False) -> bool:
        ok = True
        for sid in range(len(self.shards)):
            ok = self._try_shard_call(
                sid, "prefetch_snapshot", min_watermark, apply,
                default=False,
            ) and ok
        return ok

    # -- snapshot flushing -------------------------------------------- #

    def flush_snapshot_now(self) -> bool:
        if not self.is_leader():
            return False
        landed = False
        for sid in range(len(self.shards)):
            landed = self._try_shard_call(
                sid, "flush_snapshot", self._watermark, default=False
            ) or landed
        return landed

    def start_snapshot_flusher(
        self, interval_s: Optional[float] = None
    ) -> bool:
        interval = (
            self.config.snapshot_interval_seconds
            if interval_s is None
            else interval_s
        )
        if interval <= 0 or self._flusher_thread is not None:
            return False
        stop = threading.Event()

        def loop() -> None:
            while not stop.wait(interval):
                try:
                    self.settle_health_wall()
                    self.flush_snapshot_now()
                except Exception:  # noqa: BLE001
                    common.log.exception(
                        "sharded snapshot flusher step failed"
                    )

        t = threading.Thread(
            target=loop, name="hived-shard-flusher", daemon=True
        )
        self._flusher_stop, self._flusher_thread = stop, t
        t.start()
        return True

    def stop_snapshot_flusher(self) -> None:
        if self._flusher_stop is not None:
            self._flusher_stop.set()
        if self._flusher_thread is not None:
            self._flusher_thread.join(timeout=2.0)
        self._flusher_stop = self._flusher_thread = None

    # -- inspect aggregation ------------------------------------------ #

    def get_metrics(self) -> Dict:
        from . import supervisor as supervisor_mod

        merged: Dict = {}
        per_shard = [
            p for p in (
                self._try_shard_call(sid, "get_metrics")
                for sid in range(len(self.shards))
            ) if p is not None
        ]
        merged = _merge_metrics(per_shard)
        merged["procShards"] = len(self.shards)
        merged["shardChains"] = {
            str(b.shard_id): list(b.owned_chains) for b in self.shards
        }
        # Shared-memory filter ring (proc transport): per-frontend frame
        # counters; JSON-only (doc/observability.md).
        merged["shardRing"] = {
            "enabled": any(
                getattr(b, "_req_ring", None) is not None
                for b in self.shards
            ),
            "frames": sum(
                getattr(b, "ring_frames", 0) for b in self.shards
            ),
            "fallbacks": sum(
                getattr(b, "ring_fallbacks", 0) for b in self.shards
            ),
        }
        # One wire: per-codec transport bytes (pipe + ring frames from
        # every backend, plus the frontend's own HTTP envelope) and the
        # per-codec power-of-two frame-size histogram (JSON-only, like
        # shardRing; doc/observability.md).
        wire_bytes = {"binary": 0, "pickle": 0, "json": 0}
        frame_hist: Dict[str, Dict[str, int]] = {}
        with self._maps_lock:
            for codec, n in self._wire_env_bytes.items():
                wire_bytes[codec] = wire_bytes.get(codec, 0) + n
            resyncs = self._delta_resyncs
        for b in self.shards:
            stats_lock = getattr(b, "_stats_lock", None)
            if stats_lock is None:
                continue
            with stats_lock:
                b_bytes = dict(b.wire_bytes)
                b_hist = {c: dict(h) for c, h in b.frame_hist.items()}
            for codec, n in b_bytes.items():
                wire_bytes[codec] = wire_bytes.get(codec, 0) + n
            for codec, h in b_hist.items():
                agg = frame_hist.setdefault(codec, {})
                for bucket, count in h.items():
                    key = str(bucket)
                    agg[key] = agg.get(key, 0) + count
        merged["wireBytesTotal"] = wire_bytes
        merged["deltaSuggestedResyncCount"] = (
            merged.get("deltaSuggestedResyncCount", 0) + resyncs
        )
        merged["shardWire"] = {
            "enabled": self._wire_on,
            "frameHistogram": frame_hist,
        }
        merged["lockSharding"] = f"procs:{len(self.shards)}"
        # Fork staleness is a per-shard gauge: the merged value is the
        # OLDEST fork still being served (summing ages is meaningless).
        merged["whatifForkAgeSeconds"] = max(
            (
                s.get("whatifForkAgeSeconds", -1.0)
                for s in per_shard
            ),
            default=-1.0,
        )
        merged["leader"] = self.is_leader()
        merged["ready"] = self.is_ready()
        merged["deposedBindRefusedCount"] = (
            merged.get("deposedBindRefusedCount", 0)
            + self._deposed_bind_refused
        )
        merged["shardDownFastWaitCount"] = (
            merged.get("shardDownFastWaitCount", 0)
            + self._shard_down_fast_waits
        )
        # Control-plane weather plane: the vane and intent journal live
        # on the FRONTEND (shard writes are brokered through the parent
        # kube client) — overlay the (all-zero) summed shard-side
        # values with the frontend truth.
        merged["apiserverWeather"] = self.weather_vane.state()
        merged["apiserverWeatherEpoch"] = self.weather_vane.epoch
        jc = self.intent_journal.counters()
        merged["intentJournalDepth"] = jc["depth"]
        merged["intentJournaledCount"] = jc["journaled"]
        merged["intentSupersededCount"] = jc["superseded"]
        merged["intentCoalescedCount"] = jc["coalesced"]
        merged["intentDrainedCount"] = jc["drained"]
        merged["intentDroppedCount"] = jc["dropped"]
        merged["intentDiscardedCount"] = jc["discarded"]
        # Supervision plane (doc/observability.md): per-shard liveness
        # gauge + the restart / degraded-WAIT counters, plus explicit
        # attribution of which shards the gather above skipped.
        sup = self.supervisor.snapshot()
        merged["shardUp"] = {
            str(s["shard"]): 1 if s["status"] == supervisor_mod.STATUS_UP
            else 0
            for s in sup
        }
        merged["shardRestartCount"] = sum(s["restarts"] for s in sup)
        merged["shardDegradedWaitCount"] = sum(
            s["degradedWaits"] for s in sup
        )
        merged["shardsDown"] = [
            s["shard"] for s in sup
            if s["status"] != supervisor_mod.STATUS_UP
        ]
        # Black-box plane: shard-side audit counters already summed by
        # _merge_metrics; the recorder captures at the FRONTEND (workers
        # run with theirs off), so its counters are the frontend's.
        rec = self.recorder
        if rec is not None:
            for k, v in rec.metrics_snapshot().items():
                merged[k] = merged.get(k, 0) + v
        build = dict(merged.get("buildInfo") or {})
        build["shards"] = str(len(self.shards))
        build["flightRecorder"] = "on" if rec is not None else "off"
        merged["buildInfo"] = build
        return merged

    def get_physical_cluster_status(self) -> List[Dict]:
        merged: Dict[int, Dict] = {}
        for sid in range(len(self.shards)):
            reply = self._try_shard_call(
                sid, "inspect_physical_positions"
            )
            for i, st in reply or []:
                merged[i] = st
        return [merged[i] for i in sorted(merged)]

    def get_virtual_cluster_status(self, vcn: str) -> List[Dict]:
        merged: Dict[int, Dict] = {}
        tail: List[Dict] = []
        for sid in range(len(self.shards)):
            reply = self._try_shard_call(
                sid, "inspect_vc_positions", vcn
            )
            if reply is None:
                continue
            indexed, appended = reply
            for i, st in indexed:
                merged[i] = st
            tail.extend(appended)
        # Opportunistic-cell entries are allocation-history-ordered in a
        # single process; the merged view normalizes to address order.
        tail.sort(key=lambda st: str(st.get("cellAddress")))
        return [merged[i] for i in sorted(merged)] + tail

    def get_all_virtual_clusters_status(self) -> Dict[str, List[Dict]]:
        return {
            str(vc): self.get_virtual_cluster_status(str(vc))
            for vc in sorted(self.routing.quota_chains)
        }

    def get_cluster_status(self) -> Dict:
        return {
            "physicalCluster": self.get_physical_cluster_status(),
            "virtualClusters": self.get_all_virtual_clusters_status(),
        }

    def get_all_affinity_groups(self) -> Dict:
        items: List[Dict] = []
        for sid in range(len(self.shards)):
            reply = self._try_shard_call(sid, "get_all_affinity_groups")
            items.extend((reply or {}).get("items", []))
        # The single-process list is insertion-ordered (allocation
        # history); the merged view normalizes to name order.
        items.sort(key=lambda d: (d.get("metadata") or {}).get("name", ""))
        return {"items": items}

    def get_affinity_group(self, name: str) -> Dict:
        with self._maps_lock:
            sid = self._group_shard.get(name)
        if sid is not None:
            try:
                return self._shard_call(sid, "get_affinity_group", name)
            except ShardWorkerError:
                raise api.WebServerError(
                    503,
                    f"shard {sid} owning affinity group {name} is "
                    f"{self.supervisor.status(sid)}; retry after "
                    "resurrection",
                )
        last: Optional[api.WebServerError] = None
        for s in range(len(self.shards)):
            try:
                return self._shard_call(s, "get_affinity_group", name)
            except ShardWorkerError:
                continue
            except api.WebServerError as e:
                last = e
        raise last if last is not None else api.not_found(
            f"Affinity group {name} does not exist"
        )

    def get_health(self) -> Dict:
        payloads = [
            p for p in (
                self._try_shard_call(sid, "get_health_owned")
                for sid in range(len(self.shards))
            ) if p is not None
        ]
        merged = _merge_health(payloads)
        down = self.supervisor.down_shards()
        if down:
            merged["shardsDown"] = down
        return merged

    def get_quarantine(self) -> Dict:
        items: List[Dict] = []
        for sid in range(len(self.shards)):
            reply = self._try_shard_call(sid, "get_quarantine")
            items.extend((reply or {}).get("items", []))
        items.sort(key=lambda d: d.get("podUid", ""))
        return {"items": items}

    def get_doomed_ledger(self) -> Dict:
        merged: Dict = {"vcs": {}, "epoch": 0, "persistedEpoch": 0}
        for sid in range(len(self.shards)):
            snap = self._try_shard_call(sid, "get_doomed_ledger_owned")
            if snap is None:
                continue
            for vcn, entries in (snap.get("vcs") or {}).items():
                merged["vcs"].setdefault(vcn, []).extend(entries)
            merged["epoch"] += snap.get("epoch", 0)
            merged["persistedEpoch"] += snap.get("persistedEpoch", 0)
        for entries in merged["vcs"].values():
            entries.sort(key=lambda e: (
                str(e.get("chain")), int(e.get("level", -1)),
                str(e.get("address")),
            ))
        return merged

    def get_decisions(
        self,
        n: Optional[int] = None,
        verdict: Optional[str] = None,
        gate: Optional[str] = None,
    ) -> Dict:
        items: List[Dict] = []
        for sid in range(len(self.shards)):
            reply = self._try_shard_call(
                sid, "get_decisions", n, verdict, gate
            )
            items.extend((reply or {}).get("items", []))
        # The frontend keeps its own journal for records no shard owns:
        # `_shard` supervision lifecycle + degraded-mode WAIT verdicts.
        # Same ?verdict=/?gate= slice the workers apply server-side.
        items.extend(
            d for d in self.decisions.snapshot()
            if _decision_matches(d, verdict, gate)
        )
        # Per-shard seq counters are independent; wall time is the only
        # cross-shard recency order. Without the sort, ?n= would keep the
        # highest-numbered shard's tail and drop newer decisions from
        # earlier shards.
        items.sort(key=lambda d: d.get("wallTime", 0.0))
        return {"items": items[-n:] if n else items}

    def get_flightrecorder(self, full: bool = False) -> Dict:
        """The frontend's (pre-routing) flight recorder: one stream
        covers all shards."""
        rec = self.recorder
        if rec is None:
            return {"enabled": False}
        payload = rec.recording() if full else rec.summary()
        payload["enabled"] = True
        return payload

    def get_decision(self, key: str) -> Dict:
        last: Optional[api.WebServerError] = None
        for sid in range(len(self.shards)):
            try:
                return self._shard_call(sid, "get_decision", key)
            except ShardWorkerError:
                continue
            except api.WebServerError as e:
                last = e
        # Frontend-journaled records (degraded-mode WAITs, `_shard`
        # supervision lifecycle) live in no shard.
        rec = self.decisions.lookup(key)
        if rec is not None:
            return rec
        raise last if last is not None else api.not_found(
            f"No decision recorded for pod {key}"
        )

    def get_traces(self, n: Optional[int] = None) -> Dict:
        """Causally-stitched merged ring: worker traces carry the
        frontend trace id that routed them (``parentTraceId``, propagated
        over the pipe protocol), so shard spans nest as ``children`` of
        their frontend span; everything else orders by the wall stamp
        every trace now commits with — the same cross-process recency
        order the decision-journal merge uses. This retires PR 8's
        round-robin-interleave deviation (doc/hot-path.md)."""
        sample = None
        frontend_items = [
            {**item, "shard": "frontend"}
            for item in self.tracer.snapshot(n)
        ]
        shard_items: List[Dict] = []
        for sid in range(len(self.shards)):
            p = self._try_shard_call(sid, "get_traces", n)
            if p is None:
                continue
            sample = p.get("sample") if sample is None else sample
            shard_items.extend(
                {**item, "shard": sid}
                for item in p.get("items", [])
            )
        # Stitch: a worker trace with a parent nests under the frontend
        # trace that spawned it; orphans (worker-sampled without a
        # frontend parent, e.g. informer verbs) stay top-level.
        by_id = {t["traceId"]: t for t in frontend_items}
        top: List[Dict] = list(frontend_items)
        for item in shard_items:
            parent = by_id.get(item.get("parentTraceId"))
            if parent is not None:
                parent.setdefault("children", []).append(item)
            else:
                top.append(item)
        for t in frontend_items:
            if "children" in t:
                t["children"].sort(
                    key=lambda d: d.get("wallTime", 0.0)
                )
        top.sort(key=lambda d: d.get("wallTime", 0.0))
        if n is not None and n > 0:
            top = top[-n:]
        return {"sample": sample, "items": top}

    def get_ha(self) -> Dict:
        lead = self.leadership
        payload: Dict = {
            "haEnabled": lead is not None,
            "leader": self.is_leader(),
            "ready": self.is_ready(),
            "procShards": len(self.shards),
            "shards": [
                self._try_shard_call(
                    sid, "get_ha",
                    default={
                        "shard": sid,
                        "unavailable": True,
                        "status": self.supervisor.status(sid),
                    },
                )
                for sid in range(len(self.shards))
            ],
            # Supervision plane: per-shard liveness, restart count, and
            # last exit cause (ISSUE 17 observability satellite).
            "supervision": self.supervisor.snapshot(),
        }
        payload["weather"] = self.weather_vane.snapshot()
        payload["intentJournal"] = self.intent_journal.counters()
        if lead is not None:
            payload["identity"] = getattr(lead, "identity", "")
            payload["observedHolder"] = getattr(lead, "observed_holder", "")
            payload["leaseTransitions"] = getattr(
                lead, "transition_count", 0
            )
            payload["leaseWeather"] = getattr(lead, "lease_weather", "ok")
            payload["cannotRenewCount"] = getattr(
                lead, "cannot_renew_count", 0
            )
            payload["supersededCount"] = getattr(
                lead, "superseded_count", 0
            )
            payload["ownReacquireCount"] = getattr(
                lead, "own_reacquire_count", 0
            )
        return payload

    # -- local-transport conveniences (chaos / tests) ------------------ #

    @property
    def pod_schedule_statuses(self) -> Dict:
        """Merged status map — LOCAL transport only (the chaos harness
        and tests inspect it; production code never does)."""
        merged: Dict = {}
        for backend in self.shards:
            merged.update(backend.scheduler.pod_schedule_statuses)
        return merged

    @property
    def quarantined_pods(self) -> Dict:
        merged: Dict = {}
        for backend in self.shards:
            merged.update(backend.scheduler.quarantined_pods)
        return merged

    def get_status_pod(self, uid: str):
        """(pod, state-string) for one scheduled pod, any transport."""
        with self._maps_lock:
            sid = self._uid_shard.get(uid)
        sids = (
            [sid] if sid is not None else range(len(self.shards))
        )
        for s in sids:
            found = self._try_shard_call(s, "get_status_pod", uid)
            if found is not None:
                return found
        return None

    def shard_for_chain(self, chain: str) -> Optional[int]:
        return self._shard_of_chain.get(chain)

    def configured_node_names(self) -> List[str]:
        return sorted(self.routing.node_chains)

    def seed_preempt_rng(self, seed: int) -> None:
        """Deterministically seed every shard's victim-pick rng (tests;
        the differential suites re-seed per call so the per-shard stream
        split cannot diverge from a single process's one stream)."""
        for sid in range(len(self.shards)):
            self._try_shard_call(sid, "seed_preempt_rng", seed)

    def close(self) -> None:
        self.supervisor.stop()
        self.stop_snapshot_flusher()
        for backend in self.shards:
            backend.close()


# --------------------------------------------------------------------- #
# Merge helpers
# --------------------------------------------------------------------- #


# The one outcome classification (scheduler.recorder): trace attrs and
# both frontends' recorders share it.
_frontend_outcome = recorder_pkg.filter_outcome


def _raw_outcome(reply: Dict) -> Tuple[str, str]:
    """_frontend_outcome over an already-decoded raw-path reply DICT
    (wire keys); returns (outcome, bound node or "")."""
    if reply is None:
        return "error", ""
    if reply.get("NodeNames"):
        return "bind", str(reply["NodeNames"][0])
    if reply.get("Error"):
        return "error", ""
    if reply.get("FailedNodes") and set(reply["FailedNodes"]) != {
        constants.COMPONENT_NAME
    }:
        return "preempt", ""
    return "wait", ""


def _merge_metrics(per_shard: List[Dict]) -> Dict:
    """Sum counters, merge phase/lock-wait/histogram maps, recompute the
    latency percentiles from the merged fixed-bucket histograms (exact
    bucket counts; the percentile is the bucket upper bound — the same
    resolution Prometheus quantile queries get)."""
    merged: Dict = {}
    for snap in per_shard:
        for k, v in snap.items():
            if k in ("phases", "latencyHistograms", "lockWaitByChain"):
                continue
            if isinstance(v, bool):
                merged[k] = merged.get(k, True) and v
            elif isinstance(v, (int, float)) and "Latency" not in k:
                merged[k] = merged.get(k, 0) + v
            elif k == "recoveryMode":
                prev = merged.get(k)
                merged[k] = v if prev in (None, v) else "mixed"
            elif k not in merged:
                merged[k] = v
    phases: Dict[str, Dict] = {}
    for snap in per_shard:
        for name, entry in (snap.get("phases") or {}).items():
            agg = phases.setdefault(name, {"count": 0, "totalMs": 0.0})
            agg["count"] += entry.get("count", 0)
            agg["totalMs"] = round(
                agg["totalMs"] + entry.get("totalMs", 0.0), 3
            )
    for entry in phases.values():
        entry["avgMs"] = (
            round(entry["totalMs"] / entry["count"], 4)
            if entry["count"] else 0.0
        )
    merged["phases"] = phases
    waits: Dict[str, Dict] = {}
    for snap in per_shard:
        for chain, entry in (snap.get("lockWaitByChain") or {}).items():
            agg = waits.setdefault(chain, {"count": 0, "totalMs": 0.0})
            agg["count"] += entry.get("count", 0)
            agg["totalMs"] = round(
                agg["totalMs"] + entry.get("totalMs", 0.0), 3
            )
    merged["lockWaitByChain"] = waits
    hists: Dict[str, Dict] = {}
    for snap in per_shard:
        for name, h in (snap.get("latencyHistograms") or {}).items():
            agg = hists.get(name)
            if agg is None:
                hists[name] = {
                    # [le_seconds, cumulative_count]: cumulative counts
                    # over identical fixed buckets sum position-wise
                    # (sum of cumulatives == cumulative of sums).
                    "buckets": [list(b) for b in h.get("buckets", [])],
                    "count": h.get("count", 0),
                    "sum": round(h.get("sum", 0.0), 6),
                }
                continue
            agg["count"] += h.get("count", 0)
            agg["sum"] = round(agg["sum"] + h.get("sum", 0.0), 6)
            for mine, theirs in zip(agg["buckets"], h.get("buckets", [])):
                mine[1] += theirs[1]
    merged["latencyHistograms"] = hists
    filt = hists.get("filter")
    if filt is not None:
        merged["filterLatencyP50Ms"] = _hist_quantile(filt, 0.50)
        merged["filterLatencyP99Ms"] = _hist_quantile(filt, 0.99)
    return merged


def _hist_quantile(hist: Dict, q: float) -> float:
    """Quantile from a merged cumulative fixed-bucket histogram, in ms
    (resolution = the bucket upper bound, same as a Prometheus
    histogram_quantile)."""
    total = hist.get("count", 0)
    if not total:
        return 0.0
    rank = max(1, int(q * total + 0.999999))
    buckets = hist.get("buckets", [])
    for le, cum in buckets:
        if cum >= rank:
            return float(le) * 1e3
    # Rank fell in the +Inf overflow (observations above the top bucket):
    # clamp to the top bound — "at least this" beats reporting 0 exactly
    # when tail latency is worst.
    return float(buckets[-1][0]) * 1e3 if buckets else 0.0


def _merge_health(payloads: List[Dict]) -> Dict:
    merged: Dict = {
        "badNodes": [],
        "badChips": {},
        "drainingChips": {},
        "clock": 0,
        "damper": {"pendingCount": 0, "held": []},
        "strandedGroups": [],
        "strandedGroupCount": 0,
        "evictionPolicy": "surface",
    }
    bad_nodes: Set[str] = set()
    seen_groups: Set[str] = set()
    for p in payloads:
        bad_nodes.update(p.get("badNodes") or [])
        for n, chips in (p.get("badChips") or {}).items():
            merged["badChips"].setdefault(n, sorted(chips))
        for n, chips in (p.get("drainingChips") or {}).items():
            merged["drainingChips"].setdefault(n, sorted(chips))
        merged["clock"] = max(merged["clock"], p.get("clock", 0))
        damper = p.get("damper") or {}
        merged["damper"]["pendingCount"] += damper.get("pendingCount", 0)
        merged["damper"]["held"].extend(damper.get("held") or [])
        for rec in p.get("strandedGroups") or []:
            if rec.get("name") not in seen_groups:
                seen_groups.add(rec.get("name"))
                merged["strandedGroups"].append(rec)
        merged["evictionPolicy"] = p.get(
            "evictionPolicy", merged["evictionPolicy"]
        )
    merged["badNodes"] = sorted(bad_nodes)
    merged["strandedGroups"].sort(key=lambda r: r.get("name", ""))
    merged["strandedGroupCount"] = len(merged["strandedGroups"])
    return merged
