"""Per-chain lock sharding for the scheduling core.

The reference serializes every extender callback under one scheduler lock
(scheduler.go:104-108); PR 1 made the lock-wait share of filter latency
measurable (``lockWait`` in the phase metrics), and this module removes it
for the common case: scheduling state is almost entirely partitioned by
cell chain (free lists, VC quota ledgers, doomed accounting, the cluster
views — see doc/hot-path.md "The lock-sharding contract"), so filter/bind
calls touching disjoint chains can proceed concurrently.

Design:

- one ``threading.RLock`` per cell chain, with a TOTAL acquisition order
  (sorted chain name). Every acquisition — chain-scoped or global — takes
  its locks in that order, so lock-ordering deadlocks are impossible as
  long as no code path acquires a lock while holding a later-ordered one
  it does not already hold. The manager tracks per-thread held counts so
  that invariant (and the global-order contract below) is CHECKABLE at
  runtime, not just documented.
- chain-scoped sections (:meth:`ChainShardedLock.section`) acquire exactly
  the chains a request can touch (derived from the pod's scheduling spec
  BEFORE acquisition — see ``HivedScheduler._pod_lock_chains``).
- the global guard (:attr:`ChainShardedLock.global_guard`) acquires EVERY
  chain lock, in order: whole-cluster mutators (node/health events, pod
  lifecycle events, recovery, inspect snapshots) run under it, which also
  makes it mutually exclusive with every chain section — the semantics of
  the old single lock, at the price of N acquisitions.
- ``HIVED_GLOBAL_LOCK=1`` (or ``force_global=True``) is the differential
  escape hatch: chain sections silently widen to all chains, restoring
  the single-lock behavior exactly (tests/test_lock_sharding.py proves
  sharded ≡ global placements and metrics-visible state).

Reentrancy: RLocks make nested sections free when the needed chains are
already held (global inside global, subset inside global, same subset
inside itself — the force-bind path re-enters this way). A section must
NEVER widen while narrower locks are held (subset -> global, or subset ->
different subset): that breaks the total order. ``section`` asserts it.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

GLOBAL_LOCK_ENV = "HIVED_GLOBAL_LOCK"

# Pseudo-chain key under which global-guard waits are accumulated.
GLOBAL_KEY = "*global*"


class _Section:
    """One chain-scoped acquisition: a fresh object per use so the measured
    ``wait_s`` is race-free. ``keys`` are already sorted by the manager."""

    __slots__ = ("_mgr", "keys", "wait_s")

    def __init__(self, mgr: "ChainShardedLock", keys: Tuple[str, ...]):
        self._mgr = mgr
        self.keys = keys
        self.wait_s = 0.0

    def __enter__(self) -> "_Section":
        self.wait_s = self._mgr._acquire(self.keys, per_chain_stats=True)
        return self

    def __exit__(self, *exc) -> None:
        self._mgr._release(self.keys)


class _GlobalGuard:
    """Drop-in replacement for the framework's old single ``RLock``:
    ``with sched._lock:`` acquires every chain lock in total order. Shared
    and stateless, so one instance serves all threads."""

    __slots__ = ("_mgr",)

    def __init__(self, mgr: "ChainShardedLock"):
        self._mgr = mgr

    def __enter__(self) -> "_GlobalGuard":
        self._mgr._acquire(self._mgr.all_keys, per_chain_stats=False)
        return self

    def __exit__(self, *exc) -> None:
        self._mgr._release(self._mgr.all_keys)


class ChainShardedLock:
    """The per-chain lock table plus held-set tracking and wait metrics."""

    def __init__(self, chains: Iterable[str], force_global: Optional[bool] = None):
        self.all_keys: Tuple[str, ...] = tuple(sorted(str(c) for c in chains))
        self._locks: Dict[str, threading.RLock] = {
            c: threading.RLock() for c in self.all_keys
        }
        self.force_global = (
            os.environ.get(GLOBAL_LOCK_ENV, "0") == "1"
            if force_global is None
            else force_global
        )
        # chain (or GLOBAL_KEY) -> [acquisitions, waited seconds]. Per-chain
        # entries are only mutated while holding that chain's lock; the
        # GLOBAL_KEY entry only while holding all of them — no extra lock
        # needed.
        self._wait_stats: Dict[str, List[float]] = {
            c: [0, 0.0] for c in self.all_keys
        }
        self._wait_stats[GLOBAL_KEY] = [0, 0.0]
        # Per-thread held-lock depths: {chain: depth}. Maintained so the
        # core's cross-chain mutators can ASSERT they run under the global
        # order (require_global) and section() can assert no widening.
        self._held = threading.local()

    # -- acquisition ---------------------------------------------------- #

    def _held_map(self) -> Dict[str, int]:
        d = getattr(self._held, "d", None)
        if d is None:
            d = self._held.d = {}
        return d

    def _acquire(self, keys: Tuple[str, ...], per_chain_stats: bool) -> float:
        held = self._held_map()
        waited = 0.0
        for k in keys:
            if held.get(k, 0):
                # Reentrant: no wait, no stats double-count.
                held[k] += 1
                continue
            t0 = time.monotonic()
            self._locks[k].acquire()
            dt = time.monotonic() - t0
            waited += dt
            held[k] = 1
            if per_chain_stats:
                entry = self._wait_stats[k]
                entry[0] += 1
                entry[1] += dt
        if not per_chain_stats:
            # Global guard: one aggregated entry, updated while holding
            # every lock (so no per-chain entry can race with it either).
            entry = self._wait_stats[GLOBAL_KEY]
            entry[0] += 1
            entry[1] += waited
        return waited

    def _release(self, keys: Tuple[str, ...]) -> None:
        held = self._held_map()
        for k in reversed(keys):
            depth = held.get(k, 0)
            if depth > 1:
                held[k] = depth - 1
            else:
                held.pop(k, None)
                self._locks[k].release()

    def section(self, chains: Optional[Iterable[str]]) -> _Section:
        """A context manager acquiring the given chains (total order).
        ``None``, an empty set, an unknown chain, or force-global mode all
        widen to every chain — unknown inputs must degrade to the SAFE
        side, never to a narrower lock than the request can touch."""
        if self.force_global or chains is None:
            keys = self.all_keys
        else:
            wanted = {str(c) for c in chains}
            if not wanted or not wanted.issubset(self._locks):
                keys = self.all_keys
            else:
                keys = tuple(k for k in self.all_keys if k in wanted)
        held = self._held_map()
        if held:
            # Widening while holding a narrower set would break the total
            # order; only already-held (or subset) re-entry is legal.
            fresh = [k for k in keys if not held.get(k, 0)]
            assert not fresh or all(held.get(k, 0) for k in self.all_keys), (
                "lock-order violation: acquiring chains %s while holding %s"
                % (fresh, sorted(held))
            )
        return _Section(self, keys)

    @property
    def global_guard(self) -> _GlobalGuard:
        return _GlobalGuard(self)

    # -- introspection --------------------------------------------------- #

    def holds_all(self) -> bool:
        held = self._held_map()
        return all(held.get(k, 0) for k in self.all_keys)

    def holds_chains(self, keys: Iterable[str]) -> bool:
        """True when the calling thread holds every listed chain lock."""
        held = self._held_map()
        return all(held.get(k, 0) for k in keys)

    def require_global(self) -> None:
        """Raise unless the calling thread holds EVERY chain lock. Wired
        into the core's cross-chain mutators (node/chip health, drains,
        node deletes) as the runtime teeth of the lock-sharding contract:
        bypassing the global order is a bug the chaos sensitivity meta-test
        must catch, not a silent race (doc/hot-path.md)."""
        if not self.holds_all():
            raise RuntimeError(
                "cross-chain mutator called without the global lock order "
                "(held: %s)" % sorted(self._held_map())
            )

    def wait_snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-chain lock-wait breakdown for the metrics endpoint. Reads
        without locks: torn floats are acceptable in a diagnostic."""
        out: Dict[str, Dict[str, float]] = {}
        for k, (count, total) in list(self._wait_stats.items()):
            if count:
                out[k] = {
                    "count": int(count),
                    "totalMs": round(total * 1e3, 3),
                }
        return out
