"""The background defragmenter: checkpoint-coordinated buddy compaction.

Long-running clusters fragment: gangs arrive and depart, and the buddy
hierarchy is left with split parents whose free children cannot merge
because one small resident gang squats in the subtree. HiveD resolves
this only by chance (a squatter happens to finish). This controller
closes the loop deliberately (ROADMAP new-direction 3;
doc/fault-model.md "Elastic gang plane"):

1. **Scan** — every ``defragIntervalTicks`` health-clock ticks (the same
   event clock flap damping uses, so chaos schedules replay
   deterministically), ask the core for compaction candidates: split
   parent cells one fully-contained ALLOCATED gang away from merging,
   with room elsewhere in the chain to re-home that gang
   (``HivedCore.compaction_candidates``).
2. **Re-filter probe** — before proposing, verify a compacting placement
   actually exists: probe the opportunistic scheduler for the gang's
   exact shape with the fragment's nodes excluded. No placement → no
   proposal (the fragment is surfaced but nobody is disturbed).
3. **Drain handshake** — annotate every pod of the gang with
   ``ANNOTATION_POD_DEFRAG_MIGRATION`` (proposal generation + the nodes
   to avoid) and queue the proposal. The workload controller (or the sim
   tier / chaos harness standing in for it) checkpoints the job, deletes
   the pods, and resubmits them; the scheduler then re-filters them onto
   the compacting placement. The queued proposal is the advisory
   reservation of the target region.
4. **Cancel on fail** — if the re-filter after deletion finds no
   placement, the driver reports the failure (``report_migration``) and
   the proposal is released: annotations cleared, the gang resubmitted
   wherever it fits, ``defragCancelCount`` bumped.

Buddy fragmentation is created by GUARANTEED allocations (opportunistic
usage allocates *through* the free lists without splitting them), so the
gangs worth migrating are usually guaranteed — which is exactly why the
handshake is checkpoint-coordinated and advisory: nothing is deleted by
the scheduler; the workload controller owns the restart. Rate limits:
at most ``defragMaxMigrationsPerCycle`` proposals per cycle, one
in-flight proposal per gang, and the whole plane is OFF by default
(``defragEnable``).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from .. import common
from ..api import constants, types as api
from ..algorithm.cell import OPPORTUNISTIC_PRIORITY


class DefragController:
    """One per scheduler; every method suffixed ``_locked`` expects the
    scheduler's global order held (they read core placements and free
    lists). Proposal hand-off (``take_proposals``/``report_migration``)
    is called lock-free by drivers."""

    # Cycles a cancelled gang sits out before it may be re-proposed (a
    # failed re-filter means the fleet has no room right now; immediate
    # re-proposal would spin the handshake annotations).
    CANCEL_COOLDOWN_CYCLES = 4

    def __init__(self, sched) -> None:
        self.sched = sched
        self._seq = itertools.count(1)
        self._last_cycle_tick = 0
        self._cycle_n = 0
        # group -> cycle number before which it must not be re-proposed.
        self._cooldown: Dict[str, int] = {}
        # group name -> live proposal (annotations written, migration not
        # yet resolved). One proposal per gang, ever, until resolved.
        self._inflight: Dict[str, Dict] = {}
        # Proposals awaiting a driver (take_proposals drains).
        self._pending: List[Dict] = []
        # Annotation writes queued for the next side-effect flush:
        # (pod, {key: value-or-None}).
        self._pending_patches: List = []

    # ------------------------------------------------------------------ #
    # The cycle (scheduler lock held)
    # ------------------------------------------------------------------ #

    def tick_locked(self, clock: int) -> None:
        interval = max(1, self.sched.config.defrag_interval_ticks)
        if clock - self._last_cycle_tick < interval:
            return
        self._last_cycle_tick = clock
        self.run_cycle_locked()

    def run_cycle_locked(self) -> int:
        core = self.sched.core
        self._cycle_n += 1
        limit = max(1, self.sched.config.defrag_max_migrations_per_cycle)
        # Drop in-flight entries whose gang died (migrated or departed) —
        # their annotations died with the pods — and expired cooldowns.
        for name in [
            n for n in self._inflight if n not in core.affinity_groups
        ]:
            del self._inflight[name]
        for name in [
            n for n, until in self._cooldown.items()
            if until < self._cycle_n or n not in core.affinity_groups
        ]:
            del self._cooldown[name]
        proposed = 0
        for cand in core.compaction_candidates(limit=4 * limit):
            if proposed >= limit:
                break
            name = cand["group"]
            if name in self._inflight or name in self._cooldown:
                continue
            g = core.affinity_groups.get(name)
            if g is None:
                continue
            if not self._refilter_probe_locked(g, cand):
                continue
            proposal = {
                "generation": next(self._seq),
                "group": name,
                "vc": cand["vc"],
                "chain": cand["chain"],
                "fragment": cand["fragment"],
                "gainChips": cand["gainChips"],
                "gangChips": cand["gangChips"],
                "avoidNodes": cand["avoidNodes"],
                "blastPods": cand["blastPods"],
            }
            self._inflight[name] = proposal
            self._pending.append(proposal)
            proposed += 1
            self.sched.metrics.observe_defrag_proposal()
            self._journal(name, "defrag-propose", (
                f"fragment {cand['fragment']} ({cand['gainChips']} chips) "
                f"mergeable if {name} ({cand['gangChips']} chips, "
                f"{cand['blastPods']} pod(s)) migrates; re-filter probe "
                "found a compacting placement"
            ))
            value = common.to_json(
                {
                    "generation": proposal["generation"],
                    "fragment": proposal["fragment"],
                    "avoidNodes": proposal["avoidNodes"],
                }
            )
            for rows in g.allocated_pods.values():
                for p in rows:
                    if p is not None:
                        self._pending_patches.append(
                            (p, {
                                constants.ANNOTATION_POD_DEFRAG_MIGRATION:
                                value,
                            })
                        )
        return proposed

    def _refilter_probe_locked(self, g, cand: Dict) -> bool:
        """Would the gang fit OUTSIDE its fragment right now? Pure probe
        of the opportunistic scheduler with the fragment's nodes excluded
        from the suggested set (the 're-filter onto the compacting
        placement', run make-before-break)."""
        core = self.sched.core
        chain = cand["chain"]
        sched = core.opportunistic_schedulers.get(chain)
        if sched is None:
            return False
        avoid = set(cand["avoidNodes"])
        suggested = {
            n for n in core.configured_node_names() if n not in avoid
        }
        placement, _reason = sched.schedule(
            dict(g.total_pod_nums),
            OPPORTUNISTIC_PRIORITY,
            suggested,
            False,  # honor the suggested set: that IS the compaction
        )
        return placement is not None

    # ------------------------------------------------------------------ #
    # Driver hand-off (no scheduler locks)
    # ------------------------------------------------------------------ #

    def take_proposals(self) -> List[Dict]:
        pending, self._pending = self._pending, []
        return pending

    def report_migration(self, group: str, ok: bool, reason: str = "") -> None:
        """The driver's resolution of one proposal: ``ok`` means the gang
        was checkpointed, deleted, and re-filtered onto a compacting
        placement; failure cancels the proposal and releases its advisory
        reservation."""
        if self.sched._blackbox_top():
            # Black-box plane: the controller verb is part of the window
            # (replay re-reports so cooldown/metrics state tracks; the
            # controller's own in-flight maps are not anchored — defrag
            # replay is best-effort, doc/observability.md).
            self.sched._blackbox_record(
                "record_marker", "defrag_report",
                group=group, ok=bool(ok), reason=reason,
            )
        proposal = self._inflight.pop(group, None)
        if proposal is None:
            return
        if ok:
            self.sched.metrics.observe_defrag_migration()
            self._journal(group, "defrag-migrate", (
                f"gang migrated off fragment {proposal['fragment']} "
                f"(generation {proposal['generation']})"
            ))
        else:
            self._cooldown[group] = (
                self._cycle_n + self.CANCEL_COOLDOWN_CYCLES
            )
            self.sched.metrics.observe_defrag_cancel()
            self._journal(group, "defrag-cancel", (
                f"migration cancelled, reservation released: "
                f"{reason or 'no compacting placement at re-filter'}"
            ))
            # Clear the handshake annotation on any survivor pods (a
            # cancelled gang that was never deleted keeps running).
            g = self.sched.core.affinity_groups.get(group)
            if g is not None:
                for rows in g.allocated_pods.values():
                    for p in rows:
                        if p is not None:
                            self._pending_patches.append(
                                (p, {
                                    constants
                                    .ANNOTATION_POD_DEFRAG_MIGRATION: None,
                                })
                            )

    def flush_patches(self) -> None:
        """Write the queued handshake annotations (called from the
        scheduler's side-effect flush, outside every lock). Advisory:
        failures log and drop — the proposal itself rides in memory and
        the sim/chaos drivers consume it via take_proposals."""
        patches, self._pending_patches = self._pending_patches, []
        for pod, ann in patches:
            try:
                self.sched.kube_client.patch_pod_annotations(pod, ann)
                for k, v in ann.items():
                    if v is None:
                        pod.annotations.pop(k, None)
                    else:
                        pod.annotations[k] = v
            except Exception as e:  # noqa: BLE001
                common.log.warning(
                    "[%s]: defrag handshake annotation patch failed "
                    "(advisory): %s", pod.key, e,
                )

    # ------------------------------------------------------------------ #
    # Observability
    # ------------------------------------------------------------------ #

    def snapshot_locked(self) -> Dict:
        return {
            "enabled": True,
            "intervalTicks": self.sched.config.defrag_interval_ticks,
            "maxMigrationsPerCycle": (
                self.sched.config.defrag_max_migrations_per_cycle
            ),
            "inFlight": {
                name: {
                    k: v for k, v in p.items() if k != "avoidNodes"
                }
                for name, p in sorted(self._inflight.items())
            },
            "pendingProposals": len(self._pending),
        }

    def _journal(self, group: str, verdict: str, note: str) -> None:
        rec = self.sched.decisions.begin(
            f"group/{group}", f"group:{group}", "defrag"
        )
        rec.group = group
        rec.verdict = verdict
        rec.note(note)
        self.sched.decisions.commit(rec)


__all__ = ["DefragController"]
