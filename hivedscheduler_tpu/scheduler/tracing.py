"""Low-overhead request tracing + fixed-bucket latency histograms.

The scheduling observability plane (doc/observability.md) has three parts;
this module is the first: per-request traces. A trace is a request-scoped
bag of SPANS — named, timed phases (filter → per-chain lock wait → core
schedule → placement descent → preempt probe → bind write → informer /
recovery cycles) — kept in a bounded ring so the last N requests are always
reconstructable from a live scheduler (``/v1/inspect/traces``) without any
log scraping.

Design constraints, in order:

1. **Near-zero cost when off.** The sampling decision is one float compare
   per request (``HIVED_TRACE_SAMPLE``, default ``0.01``; ``0`` disables
   entirely). An unsampled request gets the shared :data:`NULL_TRACE`,
   whose every method is a constant no-op — no allocation, no clock reads,
   no thread-local writes. The bench A/B (``bench.py`` tracing stage)
   gates the default-sampling overhead at ≤3% of gang-schedule p50.
2. **Never inside the chain-lock order.** Spans are appended to a
   request-owned list (single-threaded by construction); only the final
   ring append shares state, and ``collections.deque.append`` is atomic
   under the GIL. Reading the ring (:meth:`Tracer.snapshot`) therefore
   never blocks a scheduling thread.
3. **No plumbing through the algorithm layers.** Deep phases (the
   placement descent's leaf-cell search) report through a module-level
   thread-local *current trace* (:func:`use` / :func:`add_span`), so the
   core and placement code need one guarded call, not a parameter on
   every signature.

The latency histograms (:class:`LatencyHistogram`) live here too: they are
the Prometheus-facing aggregate twin of the trace ring (same phases,
fixed buckets), updated under a private micro-lock that is NOT part of the
chain-lock order — a scrape can never stall a filter and vice versa.
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

TRACE_SAMPLE_ENV = "HIVED_TRACE_SAMPLE"
DEFAULT_SAMPLE = 0.01
DEFAULT_RING_CAPACITY = 256

# Fixed histogram buckets (seconds). Rationale (doc/observability.md):
# in-process filter p50 is ~1-2 ms and p99 ~15 ms at the 432-host fleet
# (doc/hot-path.md measured tables), bind writes include an apiserver RTT
# plus the RetryingKubeClient backoff schedule (up to seconds), and
# recovery replay is ~0.22 ms/pod — so the buckets run 100 µs .. 2.5 s
# with ~2.5× steps: dense where the hot path lives, wide enough that a
# retried bind still lands in a finite bucket.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)


def _env_sample() -> float:
    """Parse HIVED_TRACE_SAMPLE; malformed values degrade to the default
    (the module's degrade-never-crash contract applies to env knobs)."""
    raw = os.environ.get(TRACE_SAMPLE_ENV)
    if raw is None or raw == "":
        return DEFAULT_SAMPLE
    try:
        v = float(raw)
    except ValueError:
        return DEFAULT_SAMPLE
    return min(1.0, max(0.0, v))


class Trace:
    """One sampled request: an id, a start stamp, and a span list. Owned by
    the request thread until :meth:`finish` hands it to the ring; never
    mutated after that. ``parent`` is a causal parent trace id from
    ANOTHER process (the shards frontend propagates its filter trace id
    over the pipe protocol so worker-side spans stitch as children of the
    frontend span in the merged ``/v1/inspect/traces``)."""

    __slots__ = ("tracer", "trace_id", "name", "attrs", "t0", "spans",
                 "parent", "_finished")

    def __init__(self, tracer: "Tracer", trace_id: int, name: str,
                 attrs: Dict, parent: Optional[int] = None):
        self.tracer = tracer
        self.trace_id = trace_id
        self.name = name
        self.attrs = attrs
        self.parent = parent
        self.t0 = time.perf_counter()
        self.spans: List[Dict] = []
        self._finished = False

    def __bool__(self) -> bool:
        return True

    def add_span(self, name: str, seconds: float, **attrs) -> None:
        """Record an already-measured phase (the framework measures lock
        wait and core-schedule time anyway; re-timing them would skew the
        phase metrics the spans must agree with)."""
        d: Dict = {
            "name": name,
            "atMs": round((time.perf_counter() - self.t0) * 1e3, 4),
            "durMs": round(seconds * 1e3, 4),
        }
        if attrs:
            d.update(attrs)
        self.spans.append(d)

    def span(self, name: str, **attrs) -> "_SpanCtx":
        """Context manager measuring a phase inline."""
        return _SpanCtx(self, name, attrs)

    def note(self, **attrs) -> None:
        self.attrs.update(attrs)

    def finish(self, **attrs) -> None:
        if self._finished:
            return
        self._finished = True
        if attrs:
            self.attrs.update(attrs)
        self.tracer._commit(self)


class _SpanCtx:
    __slots__ = ("_trace", "_name", "_attrs", "_t0")

    def __init__(self, trace: Trace, name: str, attrs: Dict):
        self._trace = trace
        self._name = name
        self._attrs = attrs
        self._t0 = 0.0

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._trace.add_span(
            self._name, time.perf_counter() - self._t0, **self._attrs
        )


class _NullTrace:
    """Shared do-nothing trace for unsampled requests: falsy, and every
    method is a constant-time no-op so callers never branch."""

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def add_span(self, name: str, seconds: float, **attrs) -> None:
        pass

    def span(self, name: str, **attrs) -> "_NullSpanCtx":
        return _NULL_SPAN

    def note(self, **attrs) -> None:
        pass

    def finish(self, **attrs) -> None:
        pass


class _NullSpanCtx:
    __slots__ = ()

    def __enter__(self) -> "_NullSpanCtx":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL_TRACE = _NullTrace()
_NULL_SPAN = _NullSpanCtx()


class Tracer:
    """The sampling gate + the bounded ring of finished traces."""

    def __init__(self, sample: Optional[float] = None,
                 capacity: int = DEFAULT_RING_CAPACITY):
        self.sample = _env_sample() if sample is None else (
            min(1.0, max(0.0, float(sample)))
        )
        # deque(maxlen): appends are atomic under the GIL, old traces fall
        # off the far end — bounded memory, no lock on the hot path.
        self._ring: deque = deque(maxlen=max(1, capacity))
        self._seq = itertools.count(1)
        # Private PRNG: the sampling decision must not perturb the global
        # `random` stream (the chaos harness seeds it for determinism).
        self._rand = random.Random()
        # Micro-locked: += is a three-opcode read-modify-write, and the
        # counter feeds hived_traces_sampled_total — it must not drift
        # under concurrent sampled requests.
        self.sampled_count = 0
        self._count_lock = threading.Lock()

    def trace(self, name: str, force: bool = False,
              parent: Optional[int] = None, **attrs):
        """Start a trace, or hand back :data:`NULL_TRACE` when the request
        is not sampled. ``force=True`` bypasses sampling for rare,
        high-value cycles (recovery, informer relists) whose cost is
        negligible next to the work they wrap. A non-None ``parent``
        (a cross-process parent trace id) also forces: the parent was
        sampled upstream, so the child must exist for the stitch."""
        if not force and parent is None:
            s = self.sample
            if s <= 0.0:
                return NULL_TRACE
            if s < 1.0 and self._rand.random() >= s:
                return NULL_TRACE
        with self._count_lock:
            self.sampled_count += 1
        return Trace(self, next(self._seq), name, dict(attrs), parent)

    def _commit(self, trace: Trace) -> None:
        d = {
            "traceId": trace.trace_id,
            "name": trace.name,
            "attrs": trace.attrs,
            # Wall stamp: per-process perf_counter bases are not
            # comparable, but wall time is — the merged multi-shard ring
            # sorts on it (the same cross-process recency order the
            # decision journal merge uses).
            "wallTime": round(time.time(), 6),
            "totalMs": round(
                (time.perf_counter() - trace.t0) * 1e3, 4
            ),
            "spans": trace.spans,
        }
        if trace.parent is not None:
            d["parentTraceId"] = trace.parent
        self._ring.append(d)

    def snapshot(self, n: Optional[int] = None) -> List[Dict]:
        """Most-recent-last list of finished traces. ``list(deque)`` is
        atomic under the GIL — no lock, never blocks a scheduling thread."""
        items = list(self._ring)
        if n is not None and n >= 0:
            # n=0 means zero items; the bare [-0:] slice cannot say that.
            items = items[-n:] if n > 0 else []
        return items


# --------------------------------------------------------------------- #
# Thread-local current trace: how deep phases (placement descent) report
# without threading a trace through every algorithm signature.
# --------------------------------------------------------------------- #

_current = threading.local()


class use:
    """``with tracing.use(tr): ...`` installs ``tr`` as the thread's
    current trace for the duration (no-op for NULL_TRACE). Re-entrant:
    the previous current is restored on exit."""

    __slots__ = ("_tr", "_prev")

    def __init__(self, tr):
        self._tr = tr

    def __enter__(self):
        if self._tr:
            self._prev = getattr(_current, "tr", None)
            _current.tr = self._tr
        return self._tr

    def __exit__(self, *exc) -> None:
        if self._tr:
            _current.tr = self._prev


def current():
    """The thread's current trace, or None."""
    return getattr(_current, "tr", None)


def add_span(name: str, seconds: float, **attrs) -> None:
    """Record a span on the thread's current trace, if any. The None check
    is the entire cost when tracing is off or the request unsampled."""
    tr = getattr(_current, "tr", None)
    if tr is not None:
        tr.add_span(name, seconds, **attrs)


# --------------------------------------------------------------------- #
# Fixed-bucket latency histograms (Prometheus exposition)
# --------------------------------------------------------------------- #


class LatencyHistogram:
    """Cumulative-on-read fixed-bucket histogram. ``observe`` takes a
    private micro-lock (never part of the chain-lock order); ``snapshot``
    copies under the same lock so a scrape sees a consistent
    (buckets, sum, count) triple."""

    __slots__ = ("buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        i = 0
        bs = self.buckets
        n = len(bs)
        while i < n and seconds > bs[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += seconds
            self._count += 1

    def snapshot(self) -> Dict:
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        cumulative: List[List] = []
        running = 0
        for le, c in zip(self.buckets, counts):
            running += c
            cumulative.append([le, running])
        return {
            "buckets": cumulative,  # [le_seconds, cumulative_count]
            "count": total,         # == buckets[+Inf]
            "sum": round(s, 6),
        }
