"""Control-plane weather: apiserver outage detection + write-behind intents.

The reference HiveD assumes a healthy apiserver; our retry plane (PR 2)
absorbs transient blips and the HA plane (PR 7) fences split-brain, but a
*sustained* apiserver outage used to silently drop durable writes — the
doomed-ledger flush, snapshot persists, preempt-checkpoint annotation
patches, and evictions all counted a failure and moved on, so the next
crash recovered from state the continuous timeline never had. This module
is the weather plane (doc/fault-model.md "Control-plane weather plane"):

- :class:`WeatherVane` classifies the KubeClient's per-attempt outcome
  stream into ``clear`` / ``brownout`` / ``blackout`` with hysteresis.
  Reads and writes are tracked separately (an apiserver can serve cached
  reads while etcd rejects writes); the overall state is the worse of the
  two, and every overall transition bumps a **monotone epoch** — the
  version the weather WAIT certificates carry, so the PR 12 negative-
  filter cache answers an outage retry storm with one vector compare.

- :class:`IntentJournal` is the write-behind half: when a durable write
  exhausts its retry budget under bad weather, RetryingKubeClient
  (scheduler.kube) coalesces the *intent* — latest-wins per object key —
  into this bounded journal instead of dropping it, and reports success
  to the caller. The caller-visible world (persisted-epoch watermarks,
  eviction records, shrink commits) therefore advances exactly as it
  would under clear skies, which is what makes the post-drain durable
  state provably byte-equal to a never-outage run (the chaos convergence
  differential, tests/chaos.py). The journal drains in sequence order
  once the weather clears AND leadership is re-confirmed; a *superseded*
  leader discards — never drains — preserving the PR 7 fencing argument.

Both classes are self-contained (no framework import) so kube.py, the
chaos harness, and unit tests can use them without the scheduler.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

from .. import common
from .decisions import GATE_APISERVER_OUTAGE

# Weather states, ordered by severity — the numeric values are exported
# as-is (hived_apiserver_weather), so they are part of the metric schema.
CLEAR = 0
BROWNOUT = 1
BLACKOUT = 2

STATE_NAMES = {CLEAR: "clear", BROWNOUT: "brownout", BLACKOUT: "blackout"}

# Intent kinds (one per durable-write verb the journal covers).
INTENT_LEDGER = "ledger"      # doomed-ledger ConfigMap payload
INTENT_SNAPSHOT = "snapshot"  # snapshot ConfigMap chunk family
INTENT_PATCH = "patch"        # pod annotation merge-patch (preempt ckpt)
INTENT_EVICT = "evict"        # pod delete (stranded-gang eviction)


class _ClassTrack:
    """Failure tracking for one operation class ("read" / "write")."""

    __slots__ = (
        "window", "consecutive_failures", "consecutive_successes",
        "severity",
    )

    def __init__(self, window: int) -> None:
        self.window: deque = deque(maxlen=max(4, window))
        self.consecutive_failures = 0
        self.consecutive_successes = 0
        self.severity = CLEAR


class WeatherVane:
    """Hysteretic outage detector over the kube attempt stream.

    Per class, severity moves by these rules (evaluated per sample):

    - → ``clear`` after ``clear_after`` consecutive successes. The class
      window resets on this transition — hysteresis: a brownout's stale
      failure history must not re-trip the rate gate after the apiserver
      has demonstrably recovered.
    - → ``blackout`` after ``blackout_after`` consecutive failures
      (total unreachability, from any prior state).
    - ``clear`` → ``brownout`` when the sliding window's failure rate
      reaches ``brownout_rate`` with at least ``brownout_min_samples``
      samples, or after ``brownout_after`` consecutive failures
      (fast-path for a sudden storm on a quiet window).
    - ``blackout`` never decays to ``brownout``: recovery is only ever
      proven by the success streak, not by failures aging out.

    Overall state = max(read severity, write severity); every overall
    transition increments :attr:`epoch` (monotone — certificates compare
    it for staleness). Thread-safe; every method is O(1).
    """

    def __init__(
        self,
        window: int = 32,
        brownout_rate: float = 0.5,
        brownout_min_samples: int = 4,
        brownout_after: int = 3,
        blackout_after: int = 8,
        clear_after: int = 3,
    ) -> None:
        self.brownout_rate = float(brownout_rate)
        self.brownout_min_samples = int(brownout_min_samples)
        self.brownout_after = int(brownout_after)
        self.blackout_after = int(blackout_after)
        self.clear_after = int(clear_after)
        self._lock = threading.Lock()
        self._classes: Dict[str, _ClassTrack] = {
            "read": _ClassTrack(window),
            "write": _ClassTrack(window),
        }
        self._state = CLEAR
        self._epoch = 0
        self.transition_count = 0

    # ---------------- feeding ---------------- #

    def record(self, cls: str, ok: bool) -> None:
        """One apiserver attempt outcome. ``cls`` is "read" or "write";
        ``ok`` means the apiserver *answered* — a 4xx verdict is weather-
        wise a success (the control plane is reachable and deciding)."""
        with self._lock:
            track = self._classes.get(cls)
            if track is None:
                return
            track.window.append(0 if ok else 1)
            if ok:
                track.consecutive_successes += 1
                track.consecutive_failures = 0
            else:
                track.consecutive_failures += 1
                track.consecutive_successes = 0
            self._reclassify(track)
            overall = max(t.severity for t in self._classes.values())
            if overall != self._state:
                prev = self._state
                self._state = overall
                self._epoch += 1
                self.transition_count += 1
                common.log.warning(
                    "apiserver weather %s -> %s (epoch %d; %s class %s)",
                    STATE_NAMES[prev], STATE_NAMES[overall], self._epoch,
                    cls, STATE_NAMES[track.severity],
                )

    def _reclassify(self, track: _ClassTrack) -> None:
        if track.consecutive_successes >= self.clear_after:
            if track.severity != CLEAR:
                track.severity = CLEAR
                track.window.clear()
            return
        if track.consecutive_failures >= self.blackout_after:
            track.severity = BLACKOUT
            return
        if track.severity == CLEAR:
            n = len(track.window)
            rate = (sum(track.window) / n) if n else 0.0
            if (
                track.consecutive_failures >= self.brownout_after
                or (n >= self.brownout_min_samples
                    and rate >= self.brownout_rate)
            ):
                track.severity = BROWNOUT

    # ---------------- reading ---------------- #

    def state(self) -> int:
        return self._state

    def state_name(self) -> str:
        return STATE_NAMES[self._state]

    @property
    def epoch(self) -> int:
        return self._epoch

    def class_state(self, cls: str) -> int:
        track = self._classes.get(cls)
        return track.severity if track is not None else CLEAR

    def drain_ok(self) -> bool:
        """May the intent journal attempt a drain? Clear skies, or at
        least one class proven clear — the read class recovers first on a
        healing apiserver (probes are reads), and the first drained write
        is itself the probe that clears the write class. A failed
        optimistic drain re-journals and costs one retry round."""
        with self._lock:
            return any(t.severity == CLEAR for t in self._classes.values())

    def certificate(self) -> Dict:
        """The weather WAIT certificate: gate + the version vector the
        negative-filter cache revalidates against. Shaped like the
        shardDown certificate (gate + vector), NOT like the core's
        rejection certificate — framework._try_fast_wait branches on the
        gate before touching core-vector keys."""
        return {
            "gate": GATE_APISERVER_OUTAGE,
            "vector": {"weatherEpoch": self._epoch},
        }

    def certificate_current(self, cert: Dict) -> bool:
        """A cached weather WAIT is servable iff the epoch is unchanged
        AND the sky is still black — any transition (including heal)
        bumps the epoch, so stale verdicts self-invalidate."""
        vector = cert.get("vector") or {}
        return (
            self._state == BLACKOUT
            and vector.get("weatherEpoch") == self._epoch
        )

    def snapshot(self) -> Dict:
        """The /v1/inspect/ha weather block."""
        with self._lock:
            return {
                "state": STATE_NAMES[self._state],
                "epoch": self._epoch,
                "read": STATE_NAMES[self._classes["read"].severity],
                "write": STATE_NAMES[self._classes["write"].severity],
                "transitions": self.transition_count,
            }


class IntentJournal:
    """Bounded write-behind journal of durable-write intents.

    One entry per object key, latest-wins: re-journaling a key counts the
    displaced intent as *superseded* (its effect is contained in the
    newer one — for annotation patches the dicts are merge-coalesced,
    since applying P1 then P2 as JSON merge-patches equals applying
    ``{**P1, **P2}``). Capacity overflow drops the OLDEST entry (counted
    — the bench gate asserts zero drops at the sized capacity).

    Accounting invariant (checked by tests and the drain gate)::

        journaled == drained + superseded + dropped + discarded + depth

    Draining is sequence-ordered and stops at the first failure (the
    failed entry is restored under its original sequence number unless a
    newer intent for the key arrived meanwhile, which supersedes it).
    """

    def __init__(self, capacity: int = 512) -> None:
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._entries: Dict[str, Tuple[int, str, Any]] = {}
        self._seq = 0
        self.journaled = 0
        self.superseded = 0
        self.coalesced = 0
        self.drained = 0
        self.dropped = 0
        self.discarded = 0
        self.last_drain_error: Optional[str] = None

    # ---------------- writing ---------------- #

    def put(self, kind: str, key: str, payload: Any) -> None:
        with self._lock:
            self._seq += 1
            old = self._entries.get(key)
            if old is not None:
                _, old_kind, old_payload = old
                if kind == INTENT_PATCH and old_kind == INTENT_PATCH:
                    # Coalesce merge-patches: latest pod object, merged
                    # annotation map (None values survive — they are the
                    # RFC 7386 key deletions and must drain as such).
                    pod, annotations = payload
                    _, old_annotations = old_payload
                    payload = (
                        pod, {**dict(old_annotations), **dict(annotations)}
                    )
                    self.coalesced += 1
                self.superseded += 1
            elif len(self._entries) >= self.capacity:
                victim = min(self._entries, key=lambda k: self._entries[k][0])
                del self._entries[victim]
                self.dropped += 1
                common.log.error(
                    "intent journal full (%d): dropped oldest intent %r",
                    self.capacity, victim,
                )
            self._entries[key] = (self._seq, kind, payload)
            self.journaled += 1

    # ---------------- reading ---------------- #

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {
                "journaled": self.journaled,
                "superseded": self.superseded,
                "coalesced": self.coalesced,
                "drained": self.drained,
                "dropped": self.dropped,
                "discarded": self.discarded,
                "depth": len(self._entries),
            }

    # ---------------- resolution ---------------- #

    def discard_all(self) -> int:
        """A superseded leader's fence: the new leader owns the durable
        truth now; draining stale intents over it would be the split-
        brain write the HA plane exists to prevent."""
        with self._lock:
            n = len(self._entries)
            if n:
                self._entries.clear()
                self.discarded += n
                common.log.warning(
                    "intent journal: discarded %d intents (superseded "
                    "leader fence)", n,
                )
            return n

    def drain(self, dispatch: Callable[[str, Any], None]) -> int:
        """Dispatch every journaled intent in sequence order. Stops at
        the first dispatch failure (entry restored; retried by the next
        drain trigger). Returns the number drained this call."""
        drained = 0
        while True:
            with self._lock:
                if not self._entries:
                    break
                key = min(self._entries, key=lambda k: self._entries[k][0])
                seq, kind, payload = self._entries.pop(key)
            try:
                dispatch(kind, payload)
            except Exception as e:  # noqa: BLE001
                with self._lock:
                    if key in self._entries:
                        # A newer intent for this key landed while the
                        # drain attempt was in flight: it wins.
                        self.superseded += 1
                    else:
                        self._entries[key] = (seq, kind, payload)
                    self.last_drain_error = str(e)
                common.log.warning(
                    "intent drain stopped at %r (restored, will retry): %s",
                    key, e,
                )
                break
            with self._lock:
                self.drained += 1
                self.last_drain_error = None
            drained += 1
        return drained
