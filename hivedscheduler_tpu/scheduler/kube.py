"""Minimal Kubernetes API client + informer loop (stdlib only).

Production equivalent of the reference's client-go usage: a REST client for
the two writes/reads the scheduler needs (pod binding, list/watch of pods
and nodes), and an informer-style loop that converts watch events into the
framework's add/update/delete callbacks (reference:
pkg/scheduler/scheduler.go:132-173, pkg/internal/utils.go:291-314).

In-cluster auth: service-account bearer token + CA bundle from the standard
paths; out-of-cluster: pass the apiserver address (e.g. via kubectl proxy).
No third-party deps — urllib with a persistent-ish connection per watch.
"""

from __future__ import annotations

import json
import random
import ssl
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Iterable, Optional

from .. import common
from ..api import constants, extender as ei
from .framework import HivedScheduler, KubeClient, SchedulerMetrics
from .types import Node, Pod, is_interested
from .weather import (
    BLACKOUT,
    INTENT_EVICT,
    INTENT_LEDGER,
    INTENT_PATCH,
    INTENT_SNAPSHOT,
    IntentJournal,
    WeatherVane,
)

SA_TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"  # noqa: S105
SA_CA_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"

# In-cluster namespace (for the scheduler-owned state ConfigMap).
SA_NAMESPACE_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/namespace"


# ---------------------------------------------------------------------------
# Per-request deadline budget (doc/fault-model.md, ROADMAP "webserver request
# timeouts"): the webserver arms a thread-local deadline around each extender
# request; RetryingKubeClient refuses to start a backoff sleep that would
# cross it, so a stuck bind cannot hold an HTTP worker for the full retry
# schedule. Thread-local because extender handlers run one request per
# thread (ThreadingHTTPServer) and the kube write happens on that thread.
# ---------------------------------------------------------------------------

_REQUEST_DEADLINE = threading.local()


def set_request_deadline(budget_s: float) -> None:
    """Arm the calling thread's deadline ``budget_s`` seconds from now."""
    _REQUEST_DEADLINE.at = time.monotonic() + budget_s


def clear_request_deadline() -> None:
    _REQUEST_DEADLINE.at = None


def request_deadline_remaining() -> Optional[float]:
    """Seconds until the calling thread's deadline; None when unarmed."""
    at = getattr(_REQUEST_DEADLINE, "at", None)
    if at is None:
        return None
    return at - time.monotonic()


class KubeAPIError(Exception):
    """An apiserver request that completed with an HTTP error status.

    Carries the status code (for the RetryingKubeClient classifier) and the
    response body (apiserver Status messages say WHY a bind was rejected —
    urllib's bare HTTPError drops it, which made bind/relist failures
    undiagnosable from logs)."""

    def __init__(self, method: str, path: str, status: int, body: str):
        self.method = method
        self.path = path
        self.status = status
        self.body = body
        super().__init__(
            f"{method} {path}: HTTP {status}: {body[:512] or '<empty body>'}"
        )


def is_already_bound_conflict(e: Exception, node: str) -> bool:
    """A 409 from the Binding subresource for a pod ALREADY bound to the
    same node. bind_routine is idempotent by design (the force-bind
    executor races the extender bind path), so a duplicate Binding POST is
    a normal occurrence — the apiserver answers 409 "already assigned to
    node X". That is SUCCESS (the desired state holds), not the
    UID-precondition 409 that signals pod replacement; treating it as
    terminal would release a live gang's allocation."""
    if not (isinstance(e, KubeAPIError) and e.status == 409):
        return False
    body = e.body or ""
    # Match the QUOTED node name: apiserver messages quote it ('already
    # assigned to node "node-1"'), and a raw substring check would accept a
    # conflict for a different node whose name merely contains ours
    # (node-1 vs node-10) — silently keeping a stale allocation.
    return (
        ("already assigned" in body or "already bound" in body)
        and f'"{node}"' in body
    )


def is_retryable_kube_error(e: Exception) -> bool:
    """Classify a bind/write failure. Retryable: transport errors (refused,
    reset, timeout, TLS), apiserver 5xx, and 429 throttling. Terminal: other
    HTTP statuses — notably 404 (pod deleted before the bind landed) and 409
    (UID precondition: the pod was deleted and recreated, so the decision
    belongs to a dead incarnation).

    Non-kube backends opt in by stamping ``kube_retryable = True`` on the
    exception class (store.StoreUnavailableError): a snapshot-store outage
    is then classified exactly like an apiserver 5xx — retried, weather-
    counted, and journalable under blackout."""
    if getattr(e, "kube_retryable", False):
        return True
    if isinstance(e, KubeAPIError):
        return e.status >= 500 or e.status == 429
    if isinstance(e, urllib.error.HTTPError):  # not wrapped by _request
        return e.code >= 500 or e.code == 429
    return isinstance(e, (urllib.error.URLError, OSError, TimeoutError))


class RetryingKubeClient(KubeClient):
    """Write-path fault absorber wrapping any KubeClient.

    Retryable bind errors (transport / 5xx / 429) get capped exponential
    backoff with jitter; terminal errors (404 pod-gone, 409 UID-precondition)
    release the pod's assume-bind allocation through the scheduler so the
    gang's cells are not leaked forever — no informer DELETE ever arrives
    for a pod that was never bound. Counters land in SchedulerMetrics
    (bindRetryCount / bindGiveUpCount / bindTerminalFailureCount).

    ``sleep`` and ``jitter_rng`` are injectable so the chaos harness can run
    the real retry loop deterministically and without wall-clock delays.

    Weather plane (doc/fault-model.md "Control-plane weather plane"): every
    attempt outcome feeds the scheduler's :class:`~.weather.WeatherVane`
    (reads and writes classified separately), and when a DURABLE write —
    doomed ledger, snapshot family, preempt-checkpoint annotation patch,
    eviction — exhausts its retry budget while the vane reads BLACKOUT,
    the intent is coalesced into the :class:`~.weather.IntentJournal` and
    the call *returns success*: the caller-side watermarks advance exactly
    as under clear skies, and :meth:`maybe_drain` replays the journal
    after the weather clears and leadership is re-confirmed. ``vane`` /
    ``journal`` default to the scheduler's own (pass ``False`` to disable
    explicitly — the chaos harness's non-weather schedules do, keeping
    their pinned seeds byte-stable).
    """

    MAX_ATTEMPTS = 5
    BACKOFF_INITIAL_S = 0.2
    BACKOFF_MAX_S = 5.0
    JITTER_FRACTION = 0.25

    def __init__(
        self,
        inner: KubeClient,
        scheduler: Optional[HivedScheduler] = None,
        metrics: Optional[SchedulerMetrics] = None,
        max_attempts: int = MAX_ATTEMPTS,
        backoff_initial_s: float = BACKOFF_INITIAL_S,
        backoff_max_s: float = BACKOFF_MAX_S,
        sleep: Callable[[float], None] = time.sleep,
        jitter_rng: Optional[random.Random] = None,
        vane=None,
        journal=None,
        snapshot_store=None,
    ) -> None:
        self.inner = inner
        # Durable-state plane v2: when a SnapshotStore is configured the
        # snapshot envelope bypasses the ConfigMap chunk family entirely —
        # persist/load (and snapshot intent drains) route to the store,
        # under the SAME retry/vane/journal policy (StoreUnavailableError
        # is retryable by the shared classifier).
        self.snapshot_store = snapshot_store
        self.scheduler = scheduler
        self.metrics = metrics or (scheduler.metrics if scheduler else None)
        self.max_attempts = max_attempts
        self.backoff_initial_s = backoff_initial_s
        self.backoff_max_s = backoff_max_s
        self._sleep = sleep
        self._rng = jitter_rng or random.Random()
        self.vane: Optional[WeatherVane] = (
            None if vane is False
            else (vane or getattr(scheduler, "weather_vane", None))
        )
        self.journal: Optional[IntentJournal] = (
            None if journal is False
            else (journal or getattr(scheduler, "intent_journal", None))
        )

    def _note_weather(self, cls: str, ok: bool) -> None:
        if self.vane is not None:
            self.vane.record(cls, ok)

    def bind_pod(self, binding_pod: Pod) -> None:
        backoff = self.backoff_initial_s
        for attempt in range(1, self.max_attempts + 1):
            try:
                self.inner.bind_pod(binding_pod)
                self._note_weather("write", True)
                if attempt > 1:
                    common.log.info(
                        "[%s]: bind succeeded on attempt %d",
                        binding_pod.key, attempt,
                    )
                return
            except Exception as e:  # noqa: BLE001
                # Weather-wise a terminal verdict (404/409) is a SUCCESS:
                # the apiserver answered and decided.
                self._note_weather("write", not is_retryable_kube_error(e))
                if is_already_bound_conflict(e, binding_pod.node_name):
                    # Duplicate bind of an already-bound pod (idempotent
                    # retry / force-bind race): the desired state holds.
                    common.log.info(
                        "[%s]: pod already bound to %s; treating bind as "
                        "succeeded", binding_pod.key, binding_pod.node_name,
                    )
                    return
                if not is_retryable_kube_error(e):
                    if self.metrics is not None:
                        self.metrics.observe_bind_terminal()
                    common.log.error(
                        "[%s]: terminal bind failure, releasing allocation: "
                        "%s", binding_pod.key, e,
                    )
                    if self.scheduler is not None:
                        self.scheduler.handle_terminal_bind_failure(
                            binding_pod
                        )
                    raise
                if attempt == self.max_attempts:
                    if self.metrics is not None:
                        self.metrics.observe_bind_give_up()
                    # Keep the allocation: the pod still exists, the next
                    # filter round insists on the same placement and the
                    # force-bind path retries the write.
                    common.log.error(
                        "[%s]: bind still failing after %d attempts, giving "
                        "up this round: %s", binding_pod.key, attempt, e,
                    )
                    raise
                delay = self._next_retry_delay(
                    backoff, f"[{binding_pod.key}]: bind", e
                )
                if delay is None:
                    # Sleeping would cross the HTTP request's deadline: give
                    # up THIS round early (allocation kept, same as retry
                    # exhaustion — the next filter insists and force-bind
                    # retries the write) so the worker thread is freed.
                    raise
                if self.metrics is not None:
                    self.metrics.observe_bind_retry()
                common.log.warning(
                    "[%s]: transient bind failure (attempt %d/%d), retrying "
                    "in %.2fs: %s", binding_pod.key, attempt,
                    self.max_attempts, delay, e,
                )
                self._sleep(delay)
                backoff = min(backoff * 2, self.backoff_max_s)

    def _next_retry_delay(
        self, backoff: float, context: str, error: Exception
    ) -> Optional[float]:
        """The shared retry-scheduling policy: the next jittered delay, or
        None when sleeping that long would cross the calling thread's armed
        request deadline (counted in requestDeadlineExceededCount; the
        caller gives up its round early)."""
        delay = min(backoff, self.backoff_max_s)
        delay *= 1.0 + self.JITTER_FRACTION * self._rng.random()
        remaining = request_deadline_remaining()
        if remaining is not None and remaining < delay:
            if self.metrics is not None:
                self.metrics.observe_deadline_exceeded()
            common.log.error(
                "%s: giving up retries early: next backoff (%.2fs) would "
                "exceed the request deadline (%.2fs left): %s",
                context, delay, max(remaining, 0.0), error,
            )
            return None
        return delay

    def _retrying_op(self, describe: str, attempt_fn: Callable, cls="write"):
        """The bind retry policy for the auxiliary kube operations
        (annotation patches, scheduler-state ConfigMap reads/writes):
        transient errors back off and retry, terminal errors raise
        immediately, and an armed request deadline caps the total budget.
        Returns attempt_fn()'s value. Every attempt outcome feeds the
        weather vane under ``cls`` ("read" / "write")."""
        backoff = self.backoff_initial_s
        for attempt in range(1, self.max_attempts + 1):
            try:
                result = attempt_fn()
                self._note_weather(cls, True)
                return result
            except Exception as e:  # noqa: BLE001
                retryable = is_retryable_kube_error(e)
                self._note_weather(cls, not retryable)
                if not retryable or attempt == self.max_attempts:
                    raise
                delay = self._next_retry_delay(backoff, describe, e)
                if delay is None:
                    raise
                common.log.warning(
                    "%s: transient failure (attempt %d/%d), retrying in "
                    "%.2fs: %s", describe, attempt, self.max_attempts,
                    delay, e,
                )
                self._sleep(delay)
                backoff = min(backoff * 2, self.backoff_max_s)

    def _durable_op(
        self, describe: str, attempt_fn: Callable, kind: str, key: str,
        payload,
    ) -> None:
        """A durable write with the write-behind fallback: on an exhausted
        RETRYABLE failure while the weather vane reads blackout, the
        intent is journaled latest-wins and the call returns success —
        the caller's watermarks advance as under clear skies, and the
        journal drains after the weather heals (maybe_drain). Terminal
        errors, and exhaustion outside a blackout, raise exactly as
        before (PR 2 semantics)."""
        try:
            self._retrying_op(describe, attempt_fn)
        except Exception as e:  # noqa: BLE001
            if (
                self.journal is None
                or self.vane is None
                or not is_retryable_kube_error(e)
                or self.vane.state() != BLACKOUT
            ):
                raise
            self.journal.put(kind, key, payload)
            common.log.warning(
                "%s: retry budget exhausted under apiserver blackout; "
                "intent journaled as %r (depth %d): %s",
                describe, key, self.journal.depth(), e,
            )

    def patch_pod_annotations(self, pod, annotations) -> None:
        self._durable_op(
            f"[{pod.key}]: annotation patch",
            lambda: self.inner.patch_pod_annotations(pod, annotations),
            INTENT_PATCH, f"patch:{pod.uid}", (pod, dict(annotations)),
        )

    def persist_scheduler_state(self, payload: str) -> None:
        self._durable_op(
            "scheduler-state ConfigMap write",
            lambda: self.inner.persist_scheduler_state(payload),
            INTENT_LEDGER, "ledger", payload,
        )

    def load_scheduler_state(self) -> Optional[str]:
        # Reads share the retry policy; a missing ConfigMap is None, not an
        # error (first boot).
        return self._retrying_op(
            "scheduler-state ConfigMap read", self.inner.load_scheduler_state,
            cls="read",
        )

    def persist_snapshot(self, chunks) -> None:
        store = self.snapshot_store
        if store is not None:
            chunk_list = list(chunks)
            self._durable_op(
                f"snapshot store ({store.name}) write",
                lambda: store.persist(chunk_list),
                INTENT_SNAPSHOT, "snapshot", chunk_list,
            )
            return
        self._durable_op(
            "snapshot ConfigMap write",
            lambda: self.inner.persist_snapshot(chunks),
            INTENT_SNAPSHOT, "snapshot", list(chunks),
        )

    def load_snapshot(self):
        store = self.snapshot_store
        if store is not None:
            return self._retrying_op(
                f"snapshot store ({store.name}) read", store.load, cls="read"
            )
        return self._retrying_op(
            "snapshot ConfigMap read", self.inner.load_snapshot, cls="read"
        )

    def read_lease(self):
        return self._retrying_op(
            "leader Lease read", self.inner.read_lease, cls="read"
        )

    def write_lease(self, spec, resource_version=None) -> None:
        # A 409 (another participant won the optimistic write) is
        # non-retryable by the shared classifier and raises straight
        # through — the elector treats it correctly (leadership unchanged
        # until local expiry). Transient transport errors retry.
        self._retrying_op(
            "leader Lease write",
            lambda: self.inner.write_lease(
                spec, resource_version=resource_version
            ),
        )

    def evict_pod(self, pod: Pod) -> None:
        def attempt() -> None:
            try:
                self.inner.evict_pod(pod)
            except KubeAPIError as e:
                if e.status == 404:
                    # Already gone (deleted by a prior eviction round or
                    # by its owner): the desired state holds — eviction
                    # is idempotent.
                    return
                raise

        self._durable_op(
            f"[{pod.key}]: stranded-gang eviction", attempt,
            INTENT_EVICT, f"evict:{pod.uid}", pod,
        )

    # ------------- weather plane: probe + journal drain ------------- #

    def weather_probe(self) -> int:
        """One explicit read probe (the leader Lease — tiny, always
        present once HA is armed) feeding the vane's read class, so an
        idle blackout still heals without waiting for organic traffic.
        Returns the vane's overall state after the probe."""
        try:
            self.read_lease()
        except Exception:  # noqa: BLE001 — the probe IS the error feed
            pass
        return self.vane.state() if self.vane is not None else BLACKOUT

    def maybe_drain(self) -> int:
        """Drain the intent journal if (a) it has entries, (b) the vane
        allows a drain attempt (clear skies, or the read class proven
        clear — the first drained write is then the write-class probe),
        and (c) the scheduler still holds leadership (a deposed leader
        never drains; the superseded fence discards instead —
        framework._flush_side_effects). Returns the number drained."""
        journal = self.journal
        if journal is None or journal.depth() == 0:
            return 0
        if self.vane is not None and not self.vane.drain_ok():
            return 0
        if self.scheduler is not None and not self.scheduler.is_leader():
            return 0
        drained = journal.drain(self._dispatch_intent)
        if drained:
            common.log.warning(
                "intent journal drained %d intents (%d left)",
                drained, journal.depth(),
            )
        return drained

    def _dispatch_intent(self, kind: str, payload) -> None:
        """Replay one journaled intent against the live apiserver (full
        retry policy, NO write-behind fallback: a failure here raises to
        journal.drain, which restores the entry and stops)."""
        if kind == INTENT_LEDGER:
            self._retrying_op(
                "intent drain: scheduler-state ConfigMap write",
                lambda: self.inner.persist_scheduler_state(payload),
            )
        elif kind == INTENT_SNAPSHOT:
            store = self.snapshot_store
            if store is not None:
                self._retrying_op(
                    f"intent drain: snapshot store ({store.name}) write",
                    lambda: store.persist(payload),
                )
            else:
                self._retrying_op(
                    "intent drain: snapshot ConfigMap write",
                    lambda: self.inner.persist_snapshot(payload),
                )
        elif kind == INTENT_PATCH:
            pod, annotations = payload

            def attempt_patch() -> None:
                try:
                    self.inner.patch_pod_annotations(pod, annotations)
                except KubeAPIError as e:
                    if e.status != 404:
                        raise  # pod gone while journaled: patch is moot

            self._retrying_op(
                f"intent drain: [{pod.key}] annotation patch", attempt_patch
            )
        elif kind == INTENT_EVICT:
            pod = payload

            def attempt_evict() -> None:
                try:
                    self.inner.evict_pod(pod)
                except KubeAPIError as e:
                    if e.status != 404:
                        raise

            self._retrying_op(
                f"intent drain: [{pod.key}] eviction", attempt_evict
            )
        else:
            common.log.error("unknown journaled intent kind %r", kind)


class KubeAPIClient(KubeClient):
    """The thin K8s REST surface the scheduler needs."""

    # Bound SA tokens expire (~1h) and the kubelet rotates the file; re-read
    # it periodically the way client-go does.
    TOKEN_REFRESH_S = 300.0

    def __init__(
        self,
        base_url: str,
        token_path: Optional[str] = SA_TOKEN_PATH,
        ca_path: Optional[str] = SA_CA_PATH,
        timeout_s: float = 10.0,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self._token_path = token_path
        self._token: Optional[str] = None
        self._token_read_at = 0.0
        self._refresh_token()
        self._ssl_context: Optional[ssl.SSLContext] = None
        if self.base_url.startswith("https"):
            ctx = ssl.create_default_context()
            if ca_path:
                try:
                    ctx.load_verify_locations(ca_path)
                except OSError:
                    pass
            self._ssl_context = ctx

    # A watch request is bounded server-side (timeoutSeconds) and the socket
    # read is bounded client-side, so a half-open TCP connection can never
    # freeze an informer thread forever.
    WATCH_TIMEOUT_SECONDS = 300
    WATCH_READ_TIMEOUT_S = 330.0

    def _refresh_token(self) -> None:
        if not self._token_path:
            return
        try:
            with open(self._token_path) as f:
                self._token = f.read().strip()
            self._token_read_at = time.monotonic()
        except OSError:
            # Keep any previous token; leave the stamp so the next request
            # retries the read immediately (e.g. projected volume not yet
            # mounted at pod start).
            pass

    def _request(
        self, method: str, path: str, body: Optional[dict] = None,
        stream: bool = False, content_type: str = "application/json",
    ):
        if (
            self._token_path
            and time.monotonic() - self._token_read_at > self.TOKEN_REFRESH_S
        ):
            self._refresh_token()
        req = urllib.request.Request(
            self.base_url + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={"Content-Type": content_type},
        )
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        try:
            resp = urllib.request.urlopen(  # noqa: S310
                req,
                timeout=self.WATCH_READ_TIMEOUT_S if stream else self.timeout_s,
                context=self._ssl_context,
            )
        except urllib.error.HTTPError as e:
            # Read and attach the apiserver Status body (the reason a bind /
            # relist was rejected) plus the status code for the retry
            # classifier; HTTPError alone stringifies to just "HTTP Error
            # 409: Conflict".
            try:
                detail = e.read().decode("utf-8", "replace")
            except OSError:
                detail = ""
            raise KubeAPIError(method, path, e.code, detail) from e
        if stream:
            return resp
        with resp:
            return json.loads(resp.read() or b"{}")

    # ---------------- writes ---------------- #

    def bind_pod(self, binding_pod: Pod) -> None:
        """POST the Binding subresource, carrying our annotations — K8s
        merges Binding metadata annotations onto the pod, which is how the
        bind-info 'checkpoint' is persisted atomically with the bind
        (reference: internal/utils.go:291-314).

        SAFETY: ``metadata.uid`` is a UID *precondition* — the apiserver
        rejects the Binding if the live pod's UID differs. bind_routine
        relies on this when it performs the write outside the scheduler
        lock: a concurrent delete+recreate of the same pod name yields a
        new UID, so a stale Binding can never land on the new pod."""
        body = {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {
                "name": binding_pod.name,
                "namespace": binding_pod.namespace,
                "uid": binding_pod.uid,
                "annotations": {
                    key: binding_pod.annotations[key]
                    for key in (
                        constants.ANNOTATION_POD_LEAF_CELL_ISOLATION,
                        constants.ANNOTATION_POD_BIND_INFO,
                        constants.ANNOTATION_POD_TPU_ENV,
                    )
                    if key in binding_pod.annotations
                },
            },
            "target": {
                "apiVersion": "v1",
                "kind": "Node",
                "name": binding_pod.node_name,
            },
        }
        self._request(
            "POST",
            f"/api/v1/namespaces/{binding_pod.namespace}/pods/"
            f"{binding_pod.name}/binding",
            body,
        )

    def patch_pod_annotations(self, pod, annotations) -> None:
        """Merge-patch annotations onto a live pod (None = remove the key).
        Used to checkpoint the preemption reservation onto preemptor pods;
        JSON merge-patch nulls delete map keys (RFC 7386), which is exactly
        the clear semantics the cancel path needs."""
        self._request(
            "PATCH",
            f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}",
            {"metadata": {"annotations": dict(annotations)}},
            content_type="application/merge-patch+json",
        )

    def evict_pod(self, pod) -> None:
        """Delete a pod (stranded-gang remediation): the informer's DELETED
        event then releases its cells through the normal lifecycle."""
        self._request(
            "DELETE",
            f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}",
        )

    def _state_namespace(self) -> str:
        ns = getattr(self, "_namespace", None)
        if ns is None:
            try:
                with open(SA_NAMESPACE_PATH) as f:
                    ns = f.read().strip() or "default"
            except OSError:
                ns = "default"
            self._namespace = ns
        return ns

    def persist_scheduler_state(self, payload: str) -> None:
        """Write the scheduler-owned state ConfigMap (the doomed ledger):
        PUT replace, falling back to POST create on 404 (first boot)."""
        ns = self._state_namespace()
        name = constants.DOOMED_LEDGER_CONFIG_MAP_NAME
        body = {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": ns},
            "data": {constants.DOOMED_LEDGER_CONFIG_MAP_KEY: payload},
        }
        try:
            self._request(
                "PUT", f"/api/v1/namespaces/{ns}/configmaps/{name}", body
            )
        except KubeAPIError as e:
            if e.status != 404:
                raise
            self._request("POST", f"/api/v1/namespaces/{ns}/configmaps", body)

    def load_scheduler_state(self) -> Optional[str]:
        ns = self._state_namespace()
        name = constants.DOOMED_LEDGER_CONFIG_MAP_NAME
        try:
            obj = self._request(
                "GET", f"/api/v1/namespaces/{ns}/configmaps/{name}"
            )
        except KubeAPIError as e:
            if e.status == 404:
                return None
            raise
        return (obj.get("data") or {}).get(
            constants.DOOMED_LEDGER_CONFIG_MAP_KEY
        )

    # ---------------- snapshot ConfigMap family ---------------- #

    def _put_or_post_configmap(self, ns: str, name: str, data: Dict) -> None:
        body = {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": ns},
            "data": data,
        }
        try:
            self._request(
                "PUT", f"/api/v1/namespaces/{ns}/configmaps/{name}", body
            )
        except KubeAPIError as e:
            if e.status != 404:
                raise
            self._request("POST", f"/api/v1/namespaces/{ns}/configmaps", body)

    def persist_snapshot(self, chunks) -> None:
        """Write the snapshot chunk family (scheduler.snapshot format:
        ``chunks[0]`` is the meta header, the rest the body split at
        ~900 KB). Body chunks land in ``<name>-<i>`` ConfigMaps FIRST and
        the manifest (meta + chunk count) LAST — the commit point — so a
        crash mid-write leaves either the previous complete snapshot or a
        checksum/chunk-count mismatch the recovery ladder rejects."""
        ns = self._state_namespace()
        base = constants.SNAPSHOT_CONFIG_MAP_NAME
        body_chunks = chunks[1:]
        for i, chunk in enumerate(body_chunks):
            self._put_or_post_configmap(
                ns, f"{base}-{i}", {constants.SNAPSHOT_CHUNK_KEY: chunk}
            )
        self._put_or_post_configmap(
            ns,
            base,
            {
                constants.SNAPSHOT_META_KEY: chunks[0],
                "chunkCount": str(len(body_chunks)),
            },
        )

    def load_snapshot(self):
        ns = self._state_namespace()
        base = constants.SNAPSHOT_CONFIG_MAP_NAME
        try:
            manifest = self._request(
                "GET", f"/api/v1/namespaces/{ns}/configmaps/{base}"
            )
        except KubeAPIError as e:
            if e.status == 404:
                return None
            raise
        data = manifest.get("data") or {}
        meta = data.get(constants.SNAPSHOT_META_KEY)
        if meta is None:
            return None
        try:
            count = int(data.get("chunkCount") or 0)
        except ValueError:
            count = 0
        chunks = [meta]
        for i in range(count):
            try:
                obj = self._request(
                    "GET", f"/api/v1/namespaces/{ns}/configmaps/{base}-{i}"
                )
            except KubeAPIError as e:
                if e.status == 404:
                    # Torn family (chunk GC'd or never written): return
                    # what exists — the validation ladder's chunk-count
                    # rung rejects it and recovery falls back.
                    break
                raise
            chunks.append(
                (obj.get("data") or {}).get(constants.SNAPSHOT_CHUNK_KEY, "")
            )
        return chunks

    # ---------------- leader Lease (coordination.k8s.io) ---------------- #

    def _lease_path(self) -> str:
        ns = self._state_namespace()
        return (
            f"/apis/coordination.k8s.io/v1/namespaces/{ns}/leases/"
            f"{constants.LEADER_LEASE_NAME}"
        )

    @staticmethod
    def _micro_time(epoch_s: float) -> str:
        return time.strftime(
            "%Y-%m-%dT%H:%M:%S", time.gmtime(epoch_s)
        ) + (".%06dZ" % int((epoch_s % 1) * 1e6))

    @staticmethod
    def _from_micro_time(value) -> float:
        if not value:
            return 0.0
        try:
            import calendar

            base, _, frac = str(value).rstrip("Z").partition(".")
            t = calendar.timegm(time.strptime(base, "%Y-%m-%dT%H:%M:%S"))
            return t + (float("0." + frac) if frac else 0.0)
        except (ValueError, OverflowError):
            return 0.0

    def read_lease(self):
        """The Lease in the elector's shape: spec with NUMERIC
        acquire/renew times (epoch seconds — production electors use
        ``clock=time.time``) plus the resourceVersion for the optimistic
        write-back."""
        try:
            obj = self._request("GET", self._lease_path())
        except KubeAPIError as e:
            if e.status == 404:
                return None
            raise
        spec = obj.get("spec") or {}
        return {
            "spec": {
                "holderIdentity": spec.get("holderIdentity") or "",
                "leaseDurationSeconds": spec.get("leaseDurationSeconds"),
                "acquireTime": self._from_micro_time(spec.get("acquireTime")),
                "renewTime": self._from_micro_time(spec.get("renewTime")),
                "leaseTransitions": spec.get("leaseTransitions") or 0,
            },
            "resourceVersion": (obj.get("metadata") or {}).get(
                "resourceVersion"
            ),
        }

    def write_lease(self, spec, resource_version=None) -> None:
        ns = self._state_namespace()
        metadata: Dict = {
            "name": constants.LEADER_LEASE_NAME,
            "namespace": ns,
        }
        if resource_version is not None:
            # Optimistic concurrency: the PUT fails 409 when anyone else
            # wrote since our read — exactly the standby-race guard.
            metadata["resourceVersion"] = str(resource_version)
        body = self._lease_body(metadata, spec)
        if resource_version is None:
            # No Lease observed: the write must be CREATE-ONLY. An
            # unconditional PUT would let two standbys racing to create
            # the very first Lease both "win" (the second overwrites the
            # first with no precondition) — the POST is atomic, the loser
            # gets 409 AlreadyExists and stays a standby.
            self._request(
                "POST",
                f"/apis/coordination.k8s.io/v1/namespaces/{ns}/leases",
                body,
            )
            return
        try:
            self._request("PUT", self._lease_path(), body)
        except KubeAPIError as e:
            if e.status != 404:
                raise
            # The Lease vanished between our read and the write: recreate
            # (atomic — a racing creator wins and this raises 409).
            body["metadata"].pop("resourceVersion", None)
            self._request(
                "POST",
                f"/apis/coordination.k8s.io/v1/namespaces/{ns}/leases",
                body,
            )

    def _lease_body(self, metadata: Dict, spec: Dict) -> Dict:
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": metadata,
            "spec": {
                "holderIdentity": spec.get("holderIdentity") or "",
                "leaseDurationSeconds": int(
                    spec.get("leaseDurationSeconds") or 0
                ),
                "acquireTime": self._micro_time(
                    float(spec.get("acquireTime") or 0.0)
                ),
                "renewTime": self._micro_time(
                    float(spec.get("renewTime") or 0.0)
                ),
                "leaseTransitions": int(spec.get("leaseTransitions") or 0),
            },
        }

    # ---------------- reads ---------------- #

    def list_raw(self, path: str) -> Dict:
        """List returning the raw object (items + metadata.resourceVersion)."""
        return self._request("GET", path)

    def list_nodes(self) -> Iterable[Node]:
        for item in self.list_raw("/api/v1/nodes").get("items", []):
            yield _node_from_k8s(item)

    def list_pods(self) -> Iterable[Pod]:
        for item in self.list_raw("/api/v1/pods").get("items", []):
            yield ei.pod_from_k8s(item)

    def watch(
        self, path: str, resource_version: str = ""
    ) -> Iterable[Dict]:
        """Yield watch events from one bounded watch request. Returns when
        the server closes the stream (timeoutSeconds) — the caller tracks
        resourceVersion and relists on gaps (InformerLoop)."""
        url = (
            f"{path}?watch=true&allowWatchBookmarks=true"
            f"&timeoutSeconds={self.WATCH_TIMEOUT_SECONDS}"
        )
        if resource_version:
            url += f"&resourceVersion={resource_version}"
        resp = self._request("GET", url, stream=True)
        with resp:
            for line in resp:
                if line.strip():
                    yield json.loads(line)


def _node_from_k8s(obj: Dict) -> Node:
    status = obj.get("status") or {}
    meta = obj.get("metadata") or {}
    conditions = {
        str(c.get("type", "")): c.get("status") == "True"
        for c in status.get("conditions", [])
        if c.get("type")
    }
    return Node(
        name=str(meta.get("name", "")),
        unschedulable=bool((obj.get("spec") or {}).get("unschedulable", False)),
        ready=conditions.get("Ready", False),
        # Health-plane inputs: the device-health / drain annotations and
        # the per-chip conditions (scheduler.health parses them).
        annotations={
            str(k): str(v) for k, v in (meta.get("annotations") or {}).items()
        },
        conditions=conditions,
    )


class InformerLoop:
    """Watch nodes + pods, dispatch to the framework (reference informer
    callbacks, scheduler.go:218-304). ``start`` performs the initial list
    (recovery) before returning, so the caller can gate webserver startup on
    it exactly like the reference's WaitForCacheSync (scheduler.go:200-212).

    Fault model (what client-go reflectors provide and this loop must too):
    every watch is bounded; when it ends — or the resourceVersion is too old
    (410 Gone) — the loop RELISTS and diffs against its cache, synthesizing
    ADDED/MODIFIED/DELETED for anything that changed during the gap. That is
    what prevents a deleted pod's cells from leaking forever after a missed
    DELETE event. Reconnects back off exponentially.
    """

    BACKOFF_INITIAL_S = 0.5
    BACKOFF_MAX_S = 30.0

    def __init__(self, scheduler: HivedScheduler, client: KubeAPIClient):
        self.scheduler = scheduler
        self.client = client
        self._threads: list[threading.Thread] = []
        self._known_pods: Dict[str, Pod] = {}
        self._known_nodes: Dict[str, Node] = {}
        self._stop = threading.Event()

    def start(self) -> None:
        # The initial lists ARE recovery: bracket them with the framework's
        # recovery phases so this path replays identically to recover() —
        # the persisted doomed ledger loads first (authoritative doom
        # reconstruction) and preempting groups replay from preempt-info
        # annotations after the bound pods. finish_recovery flips /readyz
        # before the watches start (WaitForCacheSync ordering).
        # Boot recovery is always traced (force bypasses sampling): the
        # informer-driven replay is the production recovery path, and its
        # phase breakdown belongs in the trace ring like recover()'s.
        tr = self.scheduler.tracer.trace("recovery", force=True, via="informer")
        ledger_payload = None
        with tr.span("ledgerLoad"):
            try:
                # Through the scheduler's client (RetryingKubeClient in
                # production), not the raw one: a transient apiserver blip at
                # boot must not silently discard the persisted ledger.
                ledger_payload = (
                    self.scheduler.kube_client.load_scheduler_state()
                )
            except Exception as e:  # noqa: BLE001
                common.log.warning(
                    "doomed-ledger ConfigMap read failed; recovering without "
                    "it: %s", e,
                )
        with tr.span("snapshotLoad"):
            # O(delta) recovery (doc/fault-model.md "HA and snapshot
            # recovery plane"): with a valid snapshot imported, the initial
            # pod relist below IS the delta replay — unchanged bound pods
            # confirm in O(1), changed/new ones replay from annotations,
            # and finish_recovery releases imported pods the list no
            # longer carries.
            snap = self.scheduler.load_valid_snapshot()
        if snap is None:
            # A hot standby pre-applied a snapshot that is unusable now
            # (corrupted/deleted after the pre-apply): the full replay
            # below must start from a virgin core, not confirm the
            # pre-applied projection via the fingerprint fast path —
            # recover()'s discard guard, mirrored here.
            self.scheduler.discard_preapplied_state()
        self.scheduler.begin_recovery(
            ledger_payload, defer_doom_rebuild=snap is not None
        )
        try:
            # The live node list is FETCHED before the import but DISPATCHED
            # after it — recover()'s ordering: the restore reinstates
            # snapshot-time cell state (health included) wholesale, and the
            # node dispatch then acts as the health half of the delta.
            # Importing after the dispatch would wipe the live observations
            # the relist just applied (a chip that broke while we were down
            # would come back healthy until its next watch event).
            with tr.span("nodeList"):
                data = self.client.list_raw("/api/v1/nodes")
                fresh_nodes = {
                    n.name: n
                    for n in (
                        _node_from_k8s(i) for i in data.get("items", [])
                    )
                }
                nodes_rv = str(
                    (data.get("metadata") or {}).get("resourceVersion", "")
                )
            if snap is not None:
                with tr.span("snapshotImport"):
                    self.scheduler.import_snapshot(
                        snap, list(fresh_nodes.values())
                    )
            with tr.span("nodeReplay"):
                # Batched boot adds (doc/hot-path.md "Boot and transport
                # plane"): one global-mode acquisition for the whole
                # initial list instead of per-node lock churn.
                self._known_nodes.update(fresh_nodes)
                self.scheduler.add_nodes(list(fresh_nodes.values()))
            with tr.span("podReplay"):
                pods_rv = self._relist_pods(initial=True)
        except BaseException:
            # Boot failed mid-replay: do not flip /readyz or persist a
            # half-replayed ledger; the caller propagates and the process
            # restarts (pre-PR contract).
            self.scheduler._abort_recovery()
            tr.finish(outcome="aborted")
            raise
        with tr.span("preemptReplay"):
            self.scheduler.finish_recovery(list(self._known_pods.values()))
        tr.finish(
            outcome="ok",
            nodes=len(self._known_nodes),
            pods=len(self._known_pods),
        )
        for path, handler, relist, rv in (
            ("/api/v1/nodes", self._on_node_event, self._relist_nodes,
             nodes_rv),
            ("/api/v1/pods", self._on_pod_event, self._relist_pods, pods_rv),
        ):
            t = threading.Thread(
                target=self._watch_loop,
                args=(path, handler, relist, rv),
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        """Ask the watch loops to exit (they wake from backoff sleeps
        immediately; a loop blocked inside a watch read exits at the next
        server-side timeout bound)."""
        self._stop.set()

    # ---------------- relist (the recovery primitive) ---------------- #

    def _relist_nodes(self) -> str:
        data = self.client.list_raw("/api/v1/nodes")
        fresh = {
            n.name: n
            for n in (_node_from_k8s(i) for i in data.get("items", []))
        }
        for name in list(self._known_nodes):
            if name not in fresh:
                self.scheduler.delete_node(self._known_nodes.pop(name))
        for name, node in fresh.items():
            old = self._known_nodes.get(name)
            self._known_nodes[name] = node
            if old is None:
                self.scheduler.add_node(node)
            else:
                self.scheduler.update_node(old, node)
        return str((data.get("metadata") or {}).get("resourceVersion", ""))

    def _note_watermark(self, rv: str) -> None:
        """Advance the scheduler's snapshot watermark: the pod-stream
        resourceVersion below which every change is already applied (and
        therefore inside any snapshot exported from now on)."""
        if rv:
            self.scheduler.note_watermark(rv)

    def _relist_pods(self, initial: bool = False) -> str:
        data = self.client.list_raw("/api/v1/pods")
        fresh = {
            p.uid: p
            for p in (ei.pod_from_k8s(i) for i in data.get("items", []))
            if is_interested(p)
        }
        for uid in list(self._known_pods):
            if uid not in fresh:
                self.scheduler.delete_pod(self._known_pods.pop(uid))
        for uid, pod in fresh.items():
            old = self._known_pods.get(uid)
            self._known_pods[uid] = pod
            if old is None or initial:
                self.scheduler.add_pod(pod)
            else:
                self.scheduler.update_pod(old, pod)
        rv = str((data.get("metadata") or {}).get("resourceVersion", ""))
        self._note_watermark(rv)
        return rv

    # ---------------- watch loop ---------------- #

    def _watch_loop(
        self,
        path: str,
        handler: Callable[[Dict], str],
        relist: Callable[[], str],
        resource_version: str,
    ) -> None:
        backoff = self.BACKOFF_INITIAL_S
        while not self._stop.is_set():
            try:
                for event in self.client.watch(path, resource_version):
                    backoff = self.BACKOFF_INITIAL_S
                    if event.get("type") == "ERROR":
                        # Typically 410 Gone: our resourceVersion expired.
                        raise _WatchGap(str(event.get("object")))
                    rv = self._handle(event, handler)
                    if rv is None:
                        # Handler failed: do NOT advance past the event —
                        # relist to reapply the lost change.
                        raise _WatchGap("handler failure")
                    if rv:
                        resource_version = rv
                        if handler == self._on_pod_event:
                            # Bound-method equality, not identity: a fresh
                            # bound-method object is created per access.
                            self._note_watermark(rv)
                # Bounded watch ended normally; resume from the last RV.
                # Tick the health plane so held flaps settle on quiet
                # clusters (one tick per watch period, deterministic in
                # tests because test informers drive events directly).
                self.scheduler.health_tick()
            except _WatchGap as e:
                common.log.warning("watch %s gap (%s); relisting", path, e)
                # Backoff here too: a deterministically-failing handler
                # would otherwise drive an unthrottled relist loop.
                self._stop.wait(backoff)
                backoff = min(backoff * 2, self.BACKOFF_MAX_S)
                resource_version = self._relist_until_success(relist, path)
                # Advance the health plane's event clock: a flap that
                # simply stopped still settles even with no further node
                # events arriving.
                self.scheduler.health_tick()
            except (
                urllib.error.URLError, KubeAPIError, OSError,
                json.JSONDecodeError,
            ) as e:
                common.log.warning(
                    "watch %s reconnecting in %.1fs: %s", path, backoff, e
                )
                self._stop.wait(backoff)
                backoff = min(backoff * 2, self.BACKOFF_MAX_S)
                # The connection may have dropped events; relist to repair.
                resource_version = self._relist_until_success(relist, path)

    def _relist_until_success(self, relist: Callable[[], str], path: str) -> str:
        """Retry the relist (with backoff) until it succeeds. Returning ""
        after one failed attempt — the old behavior — restarted the watch
        from resourceVersion "" while the diff caches (_known_pods /
        _known_nodes) were still stale, so subsequent events were applied
        against an unsynced cache; the watch must never resume before a
        relist has actually repaired the cache."""
        backoff = self.BACKOFF_INITIAL_S
        while not self._stop.is_set():
            # Gap-repair relists are rare and diagnostic gold: always
            # trace them (the watch gap they repair may have lost events).
            tr = self.scheduler.tracer.trace(
                "informerRelist", force=True, path=path
            )
            try:
                with tr.span("relist"):
                    rv = relist()
                tr.finish(outcome="ok")
                return rv
            except Exception as e:  # noqa: BLE001
                tr.finish(outcome="error", error=str(e))
                common.log.warning(
                    "relist %s failed, retrying in %.1fs: %s", path, backoff, e
                )
                self._stop.wait(backoff)
                backoff = min(backoff * 2, self.BACKOFF_MAX_S)
        return ""

    def _handle(
        self, event: Dict, handler: Callable[[Dict], str]
    ) -> Optional[str]:
        """Returns the event's resourceVersion, or None on handler failure
        (the caller then relists instead of advancing past the event)."""
        try:
            handler(event)
        except Exception:  # noqa: BLE001
            common.log.exception("informer handler error")
            return None
        return str(
            ((event.get("object") or {}).get("metadata") or {}).get(
                "resourceVersion", ""
            )
        )

    # ---------------- event handlers ---------------- #

    def _on_node_event(self, event: Dict) -> None:
        kind = event.get("type")
        if kind == "BOOKMARK":
            return
        node = _node_from_k8s(event.get("object") or {})
        if kind == "ADDED":
            self._known_nodes[node.name] = node
            self.scheduler.add_node(node)
        elif kind == "MODIFIED":
            old = self._known_nodes.get(node.name)
            self._known_nodes[node.name] = node
            if old is None:
                self.scheduler.add_node(node)
            else:
                self.scheduler.update_node(old, node)
        elif kind == "DELETED":
            self._known_nodes.pop(node.name, None)
            self.scheduler.delete_node(node)

    def _on_pod_event(self, event: Dict) -> None:
        kind = event.get("type")
        if kind == "BOOKMARK":
            return
        pod = ei.pod_from_k8s(event.get("object") or {})
        if kind == "ADDED":
            if is_interested(pod):
                self._known_pods[pod.uid] = pod
                self.scheduler.add_pod(pod)
        elif kind == "MODIFIED":
            old = self._known_pods.get(pod.uid)
            if old is None:
                # First sighting (became interested late, or its ADDED fell
                # in a watch gap): admit it now.
                if is_interested(pod):
                    self._known_pods[pod.uid] = pod
                    self.scheduler.add_pod(pod)
                return
            self._known_pods[pod.uid] = pod
            self.scheduler.update_pod(old, pod)
        elif kind == "DELETED":
            self._known_pods.pop(pod.uid, None)
            self.scheduler.delete_pod(pod)


class _WatchGap(Exception):
    """The watch stream reported an ERROR event (e.g. 410 Gone)."""
