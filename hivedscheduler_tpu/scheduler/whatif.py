"""Shadow what-if plane: snapshot-forked admission forecasts with ETAs.

HiveD guarantees *where* a gang lands but a WAIT verdict says nothing
about *when* — a silent queue. This module answers, per pending or
hypothetical gang, "when will this schedule, what would it preempt, and
which gate blocks it until then", by composing three planes that already
exist:

- **Fork.** A shadow :class:`~.framework.HivedScheduler` is built from
  the live scheduler's durable projection (``export_fork_body`` — the
  snapshot walk of PR 7 without the ConfigMap round-trip) through the HA
  standby's pre-apply path (``_import_snapshot_state``): the fork's core
  is the exact assumed state the next live filter call would schedule
  against, including in-flight assume-binds.
- **Replay.** The caller-supplied horizon (departures, drains, chip
  faults) replays against the fork through the REAL scheduling verbs —
  the same filter/preempt/delete protocol the sim tier's TraceDriver
  speaks (PR 9). After each horizon step the still-waiting gangs are
  re-probed in FIFO order; the first step at which a gang places is its
  promised ETA, and a guaranteed gang's probe runs the full preemption
  protocol on the fork, so "what would it preempt" is the actual victim
  set, not a heuristic.
- **Certificates.** Every WAIT verdict already carries a rejection
  certificate (failed gate + the version vector the attempt read, PR
  12). The live certificate seeds the forecast's blocking gate, and the
  FORK's own certificates gate the replay: a waiting gang is re-probed
  only when the fork's version vector moved for it — the same
  no-op-deletion argument as the negative-filter cache, so a forecast
  over a deep queue costs O(changes), not O(queue x events).

**The read-only contract, with teeth.** A forecast must never mutate
live state. The fork is a separate object graph by construction, but
construction is not a proof — so the plane arms a ``lock_validator``-
style audit on the LIVE scheduler: while a forecast thread is inside its
shadow section, any live-core mutator entry (``core.write_guard``) or
live framework verb (``framework._mutation_guard``) raises
:class:`ShadowWriteError` instead of corrupting served state. The
sensitivity meta-test (tests/test_whatif.py) proves a fork wired to the
live scheduler is caught.

Serving: ``POST /v1/inspect/whatif`` (webserver) with three modes —
``spec`` (one hypothetical gang), ``queue: true`` (score the whole live
waiting queue FIFO, stamping ``predictedWaitS`` onto each gang's
decision-journal WAIT record), and ``capacityTrace`` (capacity
planning: replay tomorrow's trace against today's snapshot on the fork
via TraceDriver and report SLO risk). The ``forecasts`` section of a
reply is deterministic — same snapshot + same horizon => bit-identical
(tests assert it); wall-clock costs live only under ``meta``.

Metrics: ``hived_whatif_*`` (doc/observability.md) — forecast counters,
fork pod count, and fork staleness (age of the last fork; -1 before the
first).
"""

from __future__ import annotations

import random
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from .. import common
from ..api import extender as ei, types as api
from . import snapshot as snapshot_mod, tracing, wire as wire_mod
from .framework import (
    HivedScheduler,
    NullKubeClient,
    PodState,
    WHATIF_EMPTY_METRICS,
)
from .types import (
    Node,
    Pod,
    apply_node_fault_event,
    extract_pod_scheduling_spec,
)

# Forecast verdicts.
VERDICT_SCHEDULE = "schedule"   # places at predictedWaitS (0 = now)
VERDICT_BLOCKED = "blocked"     # not within the confidence horizon


class ShadowWriteError(RuntimeError):
    """A shadow-forecast thread reached a LIVE-scheduler mutator: the
    read-only-fork audit's teeth (see module docstring)."""


def restored_node_baseline(core, name: str) -> Node:
    """The Node object a restored core's health state corresponds to —
    the baseline horizon fault events apply OVER. A fresh healthy Node
    here would be wrong twice: the first update_node would heal restored
    node badness ("first observation always applies" in the damper), and
    an empty drain annotation would lift restored drains — phantom
    capacity, optimistic promises. Reconstructs ready from bad_nodes and
    the device-health / drain annotations from the restored chip
    records (the inverse of scheduler.health's parse)."""
    from ..api import constants as const

    annotations: Dict[str, str] = {}
    bad = core.bad_chips.get(name)
    if bad:
        annotations[const.ANNOTATION_NODE_DEVICE_HEALTH] = ",".join(
            sorted(str(c) for c in bad)
        )
    draining = core.draining_chips.get(name)
    if draining:
        all_chips = core.node_chip_indices(name)
        annotations[const.ANNOTATION_NODE_DRAIN] = (
            "*"
            if all_chips and {int(c) for c in draining} >= set(all_chips)
            else ",".join(sorted(str(c) for c in draining))
        )
    return Node(
        name=name,
        ready=name not in core.bad_nodes,
        annotations=annotations,
    )


class ShadowFork:
    """One forked shadow scheduler plus the group bookkeeping the horizon
    replay needs (group name -> restored pods, uid -> group)."""

    def __init__(self, sched: HivedScheduler, body: Dict):
        self.sched = sched
        self.nodes: List[str] = sorted(sched.core.configured_node_names())
        self.groups: "OrderedDict[str, List[Pod]]" = OrderedDict()
        self.uid_group: Dict[str, str] = {}
        for rec in body.get("pods") or []:
            gname = str(rec["spec"]["affinityGroup"]["name"])
            status = sched.pod_schedule_statuses.get(rec["uid"])
            if status is None:
                continue
            self.groups.setdefault(gname, []).append(status.pod)
            self.uid_group[rec["uid"]] = gname
        self.pod_count = sum(len(p) for p in self.groups.values())
        # Node objects for the horizon's fault vocabulary, seeded from
        # the RESTORED health state (restored_node_baseline) so a
        # horizon event is a delta on today's truth, never a heal.
        self._node_cache: Dict[str, Node] = {}

    def node(self, name: str) -> Node:
        n = self._node_cache.get(name)
        if n is None:
            n = self._node_cache[name] = restored_node_baseline(
                self.sched.core, name
            )
        return n

    def kill_group(self, gname: str) -> int:
        """Delete a restored gang from the fork (departure or preemption
        victim); idempotent."""
        pods = self.groups.pop(gname, None) or []
        for p in pods:
            self.sched.delete_pod(p)
            self.uid_group.pop(p.uid, None)
        return len(pods)

    def register(self, gname: str, pods: List[Pod]) -> None:
        """Index a gang the FORECAST placed on the fork, so a later
        forecast gang's preemption can name (and kill) it exactly like a
        restored gang — without this, victims with synthetic probe uids
        would be unmapped and the preemptor falsely 'blocked'."""
        self.groups[gname] = list(pods)
        for p in pods:
            self.uid_group[p.uid] = gname


class _ForecastGang:
    """One waiting (or hypothetical) gang being forecast."""

    __slots__ = (
        "name", "vc", "priority", "pods", "uids", "live_gate", "cert",
        "gate", "detail",
    )

    def __init__(self, name, vc, priority, pods, uids=None, live_gate=None):
        self.name = name
        self.vc = vc
        self.priority = priority
        self.pods: List[Pod] = pods
        # The LIVE pods' uids (queue mode): predictedWaitS is stamped
        # onto their decision-journal WAIT records.
        self.uids: List[str] = uids or []
        self.live_gate = live_gate  # gate from the live rejection cert
        self.cert: Optional[Dict] = None  # the FORK's latest certificate
        self.gate: Optional[str] = live_gate
        self.detail: Optional[Dict] = None

    @property
    def guaranteed(self) -> bool:
        return self.priority is not None and self.priority >= 0


class WhatIfPlane:
    """The what-if engine attached to one live scheduler."""

    def __init__(self, sched: HivedScheduler):
        self.sched = sched
        self._tls = threading.local()
        self._lock = threading.Lock()  # serializes forecasts
        # Counters (metrics_snapshot; doc/observability.md).
        self.forecast_count = 0
        self.forecast_gang_count = 0
        self.fork_count = 0
        self.audit_violations = 0
        self.last_fork_pods = 0
        self.last_fork_at: Optional[float] = None
        self.last_forecast_s = 0.0
        self._arm_audit()

    # ---------------- the read-only-fork audit ---------------- #

    def _arm_audit(self) -> None:
        """Install the teeth on the LIVE scheduler. Idempotent, and
        re-run before every forecast: recovery paths replace the core
        object (_reset_for_full_replay), which would silently shed the
        guard."""
        self.sched._mutation_guard = self._audit
        self.sched.core.write_guard = self._audit

    def _audit(self) -> None:
        if getattr(self._tls, "shadow", 0):
            self.audit_violations += 1
            raise ShadowWriteError(
                "shadow what-if forecast attempted to mutate LIVE "
                "scheduler state (the fork must be the only subject a "
                "forecast drives)"
            )

    class _ShadowSection:
        def __init__(self, plane: "WhatIfPlane"):
            self.plane = plane

        def __enter__(self):
            tls = self.plane._tls
            tls.shadow = getattr(tls, "shadow", 0) + 1
            return self

        def __exit__(self, *exc):
            self.plane._tls.shadow -= 1
            return False

    def shadow_section(self) -> "WhatIfPlane._ShadowSection":
        """While entered, the calling thread may only drive forks — any
        live-scheduler mutation raises ShadowWriteError."""
        return self._ShadowSection(self)

    # ---------------- fork construction ---------------- #

    def build_fork(self, seed: int = 0) -> ShadowFork:
        """Fork the shadow scheduler from the live durable projection —
        the HA standby's pre-apply path, minus the ConfigMap round-trip.
        Raises 503 while the projection is transient (a preemption
        resolving or a gang mid-admission); the window is one scheduling
        event, callers simply retry."""
        self._arm_audit()
        body = self.sched.export_fork_body()
        if body is None:
            raise api.WebServerError(
                503,
                "live projection is transient (preemption or gang "
                "admission in flight); retry the what-if call",
            )
        # The fork hop rides the snapshot wire codec (scheduler.wire):
        # pack + unpack gives the fork a codec-fresh body that shares NO
        # mutable sub-object with the live export — the same isolation
        # the ConfigMap round-trip used to imply, at C-speed JSON cost —
        # and keeps this hop differential-testable against the HA
        # restore path (same frame, same validation ladder). A refusal
        # here is a codec bug, not a staleness condition: fall back to
        # the direct dict and log, never fail the forecast.
        if wire_mod.enabled():
            fp = str(getattr(self.sched, "_config_fingerprint", "") or "")
            try:
                packed = snapshot_mod.encode_body_wire(
                    body, fp, getattr(self.sched, "_watermark", 0)
                )
                unpacked, reason = snapshot_mod.decode_body_wire(packed, fp)
            except Exception:  # noqa: BLE001
                common.log.exception("what-if fork wire hop failed")
                unpacked, reason = None, "encode raised"
            if unpacked is not None:
                body = unpacked
            else:
                common.log.warning(
                    "what-if fork wire hop refused (%s); forking from "
                    "the direct export", reason,
                )
        fork = HivedScheduler(
            self.sched.config,
            kube_client=NullKubeClient(),
            auto_admit=True,
            global_lock=True,
            trace_sample=0.0,
            # Force binds are live-cluster side effects; on a fork they
            # would also be BACKGROUND fork mutations racing the replay
            # (non-deterministic forecasts). The assume-bind state is all
            # a forecast reads — drop them.
            force_bind_executor=lambda fn: None,
            # The fork is a forecast instrument, not a deployment: it
            # must neither record its shadow verbs into a black box nor
            # burn forecast latency auditing itself (the LIVE scheduler's
            # auditor covers the state forecasts are derived from).
            flight_recorder=False,
            live_audit=False,
        )
        fork._import_snapshot_state(body, live_names=None)
        with fork._lock:
            # Recovery-only trackers; the fork serves immediately.
            fork._snapshot_pending.clear()
            fork._snapshot_claims.clear()
        # Deterministic preempt victim-node picks per forecast seed, so
        # repeated forecasts at one snapshot epoch are bit-identical.
        fork.core.preempt_rng = random.Random(seed)
        shadow = ShadowFork(fork, body)
        self.fork_count += 1
        self.last_fork_pods = shadow.pod_count
        self.last_fork_at = time.monotonic()
        return shadow

    # ---------------- the forecast engine ---------------- #

    def _attempt(
        self, fork: ShadowFork, gang: _ForecastGang
    ) -> Tuple[bool, Optional[Dict]]:
        """One scheduling attempt for the gang on the fork — the same
        protocol the extender (and the sim driver) speaks: filter every
        member; on failure a guaranteed gang runs the preemption probe,
        kills its victims ON THE FORK, and re-filters. Returns
        (placed, preemption detail)."""
        sched = fork.sched
        if self._filter_all(fork, gang.pods):
            fork.register(gang.name, gang.pods)
            return True, None
        if not gang.guaranteed:
            return False, None
        result = sched.preempt_routine(
            ei.ExtenderPreemptionArgs(
                pod=gang.pods[0],
                node_name_to_meta_victims={
                    n: ei.MetaVictims() for n in fork.nodes
                },
            )
        )
        victim_uids = {
            mp.uid
            for mv in result.node_name_to_meta_victims.values()
            for mp in mv.pods
        }
        if not victim_uids:
            return False, None
        victims: List[Dict] = []
        for gname in sorted(
            {fork.uid_group.get(u, "") for u in victim_uids} - {""}
        ):
            for p in fork.groups.get(gname, []):
                victims.append(
                    {
                        "pod": p.key,
                        "uid": p.uid,
                        "node": p.node_name,
                        "group": gname,
                    }
                )
            fork.kill_group(gname)
        if self._filter_all(fork, gang.pods):
            fork.register(gang.name, gang.pods)
            return True, {
                "victimPods": len(victims),
                "victims": victims,
            }
        # Cancel: release the fork-side reservation so a blocked gang
        # never parks shadow capacity it cannot use (the extender's
        # cancel shape — preempt with no candidates).
        sched.preempt_routine(
            ei.ExtenderPreemptionArgs(
                pod=gang.pods[0], node_name_to_meta_victims={}
            )
        )
        for p in gang.pods:
            sched.delete_pod(p)
        return False, None

    def _filter_all(self, fork: ShadowFork, pods: List[Pod]) -> bool:
        """Filter every member; partial failure releases the placed
        prefix (the framework's partial-gang release)."""
        for p in pods:
            r = fork.sched.filter_routine(
                ei.ExtenderArgs(pod=p, node_names=fork.nodes)
            )
            if not r.node_names:
                for q in pods:
                    fork.sched.delete_pod(q)
                return False
        return True

    def _refresh_cert(self, fork: ShadowFork, gang: _ForecastGang) -> None:
        rec = fork.sched.decisions.lookup(gang.pods[0].uid)
        cert = (rec or {}).get("certificate")
        gang.cert = cert
        if cert is not None and cert.get("gate"):
            gang.gate = cert["gate"]

    def _apply_event(self, fork: ShadowFork, ev: Dict) -> None:
        """One horizon event on the fork: a departure, or a fault in the
        sim driver's node vocabulary keyed by node NAME (the shared
        scheduler.types.apply_node_fault_event implementation — the two
        replay engines cannot drift)."""
        kind = str(ev.get("kind") or "")
        if kind == "depart":
            fork.kill_group(str(ev.get("group") or ""))
            return
        name = str(ev.get("node") or "")
        if not name:
            return
        old = fork.node(name)
        new = apply_node_fault_event(old, ev)
        if new is None:
            return  # unknown kinds are ignored, not errors
        fork._node_cache[name] = new
        fork.sched.update_node(old, new)

    def run_forecast(
        self,
        fork: ShadowFork,
        gangs: List[_ForecastGang],
        events: List[Dict],
        duration_s: float,
    ) -> List[Dict]:
        """Replay the horizon on the fork, re-probing the waiting gangs
        in FIFO order after each step. Certificate-gated: a gang whose
        FORK certificate's version vector is unchanged is provably
        blocked identically and is skipped (the wait-cache argument, one
        layer up). Runs inside the shadow section — live mutations
        raise."""
        pending = list(gangs)
        done: Dict[str, Dict] = {}

        def probe_round(t: float) -> None:
            t0 = time.perf_counter()
            probed = 0
            for gang in list(pending):
                if gang.cert is not None and fork.sched.core.certificate_current(
                    gang.cert
                ):
                    continue  # provably the same WAIT: skip the probe
                probed += 1
                placed, preempt_detail = self._attempt(fork, gang)
                if placed:
                    done[gang.name] = {
                        "gang": gang.name,
                        "vc": gang.vc,
                        "priority": gang.priority,
                        "members": len(gang.pods),
                        "verdict": VERDICT_SCHEDULE,
                        "predictedWaitS": round(t, 3),
                        "blockingGate": gang.gate if t > 0 else None,
                        "preemption": preempt_detail,
                    }
                    pending.remove(gang)
                else:
                    self._refresh_cert(fork, gang)
            # Forecast observability (doc/observability.md): each re-probe
            # round is a child span on the live trace ring, so forecast
            # cost shows up in /v1/inspect/traces alongside filter and
            # preempt instead of being invisible.
            tracing.add_span(
                "queueReprobe", time.perf_counter() - t0,
                horizonT=round(t, 3), probed=probed,
                pending=len(pending),
            )

        def event_key(e: Dict):
            # The seq tiebreak (sim_sample attaches the driver's heap
            # seq) keeps same-instant departures in the caller's own
            # deterministic order; events without one sort after, by
            # kind then full content.
            seq = e.get("seq")
            return (
                float(e.get("t", 0.0)),
                float(seq) if isinstance(seq, (int, float)) else float("inf"),
                str(e.get("kind", "")),
                str(e),
            )

        with self.shadow_section():
            probe_round(0.0)
            for ev in sorted(events, key=event_key):
                if not pending:
                    break
                t = float(ev.get("t", 0.0))
                if t > duration_s:
                    break
                self._apply_event(fork, ev)
                probe_round(max(t, 0.0))
        for gang in pending:
            done[gang.name] = {
                "gang": gang.name,
                "vc": gang.vc,
                "priority": gang.priority,
                "members": len(gang.pods),
                "verdict": VERDICT_BLOCKED,
                "predictedWaitS": None,
                "blockingGate": gang.gate,
                "preemption": None,
            }
        # FIFO order of the input queue, preserved in the reply.
        return [done[g.name] for g in gangs]

    # ---------------- gang construction ---------------- #

    def _gang_from_spec(self, spec: Dict) -> _ForecastGang:
        """A hypothetical gang from the sim trace vocabulary:
        name/vc/leafType/pods/chips/priority."""
        from ..sim import fleet

        try:
            name = str(spec["name"])
            vc = str(spec["vc"])
            leaf_type = str(spec["leafType"])
            n_pods = int(spec["pods"])
            chips = int(spec["chips"])
            priority = int(spec["priority"])
        except (KeyError, TypeError, ValueError) as e:
            raise api.bad_request(
                f"whatif spec needs name/vc/leafType/pods/chips/priority: {e}"
            )
        group = {
            "name": name,
            "members": [{"podNumber": n_pods, "leafCellNumber": chips}],
        }
        pods = [
            fleet.make_pod(
                f"{name}-wf{i}", f"{name}-wfu{i}", vc, priority,
                leaf_type, chips, group,
            )
            for i in range(n_pods)
        ]
        return _ForecastGang(name, vc, priority, pods)

    def waiting_gangs(self) -> List[_ForecastGang]:
        """The LIVE waiting queue as forecast gangs, FIFO by first-filter
        order (pod_schedule_statuses preserves insertion order). Probe
        pods are synthesized to the gang's FULL member count from a
        representative waiting pod's annotations, so the fork probe
        places the whole gang even when only some members have filtered
        yet. Each gang carries its live rejection certificate's gate —
        the forecast starts at the exact blocking gate the live WAIT
        recorded."""
        out: "OrderedDict[str, Dict]" = OrderedDict()
        for uid, st in list(self.sched.pod_schedule_statuses.items()):
            if st.pod_state != PodState.WAITING:
                continue
            pod = st.pod
            try:
                spec = extract_pod_scheduling_spec(pod)
            except api.WebServerError:
                continue
            gname = (
                spec.affinity_group.name
                if spec.affinity_group is not None
                else pod.name
            )
            entry = out.get(gname)
            if entry is None:
                members = (
                    [
                        (int(m.pod_number), int(m.leaf_cell_number))
                        for m in spec.affinity_group.members
                    ]
                    if spec.affinity_group is not None
                    else [(1, int(spec.leaf_cell_number))]
                )
                rec = self.sched.decisions.lookup(uid) or {}
                cert = rec.get("certificate") or {}
                entry = out[gname] = {
                    "vc": str(spec.virtual_cluster),
                    "priority": spec.priority,
                    "members": members,
                    "rep": pod,
                    "uids": [],
                    "gate": cert.get("gate"),
                }
            entry["uids"].append(uid)
        gangs: List[_ForecastGang] = []
        for gname, e in out.items():
            gangs.append(
                _ForecastGang(
                    gname, e["vc"], e["priority"],
                    self._member_probe_pods(gname, e["rep"], e["members"]),
                    uids=e["uids"], live_gate=e["gate"],
                )
            )
        return gangs

    @staticmethod
    def _member_probe_pods(gname, rep: Pod, members) -> List[Pod]:
        """Probe pods for the gang's FULL member list, cloned from a
        representative waiting pod. A heterogeneous gang's member
        entries differ in leafCellNumber, and a pod's own spec must name
        ITS member's leaf count — one rewritten spec annotation per
        distinct entry (yaml.safe_dump sorts keys: deterministic)."""
        import yaml

        from ..api import constants as const

        spec_text = rep.annotations.get(
            const.ANNOTATION_POD_SCHEDULING_SPEC, ""
        )
        try:
            spec_d = yaml.safe_load(spec_text)
        except yaml.YAMLError:
            spec_d = None
        if not isinstance(spec_d, dict):
            spec_d = None
        pods: List[Pod] = []
        i = 0
        for pod_number, leaf_num in members:
            annotations = dict(rep.annotations)
            if spec_d is not None and spec_d.get("leafCellNumber") != leaf_num:
                rewritten = dict(spec_d)
                rewritten["leafCellNumber"] = leaf_num
                annotations[const.ANNOTATION_POD_SCHEDULING_SPEC] = (
                    yaml.safe_dump(rewritten)
                )
            for _ in range(max(1, pod_number)):
                pods.append(
                    Pod(
                        name=f"{gname}-wf{i}",
                        uid=f"{gname}-wfu{i}",
                        annotations=annotations,
                        resource_limits=dict(rep.resource_limits),
                    )
                )
                i += 1
        return pods

    # ---------------- serving ---------------- #

    def serve(self, payload: Dict) -> Dict:
        """One POST /v1/inspect/whatif request (see module docstring for
        the modes). Serialized per plane: forecasts are CPU-bound fork
        replays; two concurrent ones would just thrash."""
        if not isinstance(payload, dict):
            raise api.bad_request("whatif payload must be a JSON object")
        with self._lock:
            return self._serve_locked(payload)

    def _serve_locked(self, payload: Dict) -> Dict:
        horizon = payload.get("horizon") or {}
        events = list(horizon.get("events") or [])
        try:
            duration_s = float(
                horizon.get("durationS")
                or max(
                    [float(e.get("t", 0.0)) for e in events], default=0.0
                )
            )
        except (TypeError, ValueError):
            raise api.bad_request("horizon.durationS must be a number")
        seed = int(payload.get("seed") or 0)
        t0 = time.perf_counter()
        if payload.get("capacityTrace") is not None:
            return self._serve_capacity(payload, seed, t0)
        if payload.get("spec") is not None:
            mode = "spec"
            gangs = [self._gang_from_spec(payload["spec"])]
        elif payload.get("queue"):
            mode = "queue"
            gangs = self.waiting_gangs()
        else:
            raise api.bad_request(
                "whatif payload needs one of: spec, queue: true, "
                "capacityTrace"
            )
        # Forecast cost belongs in the trace ring next to filter/preempt
        # (doc/observability.md): force-traced like recovery — rare,
        # high-value, and the whole point is visibility.
        tr = self.sched.tracer.trace("whatif", force=True, mode=mode)
        with tr.span("forkBuild"):
            fork = self.build_fork(seed)
        fork_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        with tracing.use(tr):
            with tr.span("horizonReplay", events=len(events)):
                forecasts = self.run_forecast(
                    fork, gangs, events, duration_s
                )
        forecast_s = time.perf_counter() - t1
        tr.finish(gangs=len(forecasts))
        if mode == "queue" and payload.get("stamp", True):
            by_name = {f["gang"]: f for f in forecasts}
            for gang in gangs:
                f = by_name[gang.name]
                for uid in gang.uids:
                    self.sched.decisions.stamp_predicted_wait(
                        uid, f["predictedWaitS"], horizon_s=duration_s
                    )
        self.forecast_count += 1
        self.forecast_gang_count += len(forecasts)
        self.last_forecast_s = fork_s + forecast_s
        return {
            "mode": mode,
            # Deterministic: same snapshot + same horizon => identical.
            "forecasts": forecasts,
            "meta": self._meta(
                fork, len(events), duration_s, fork_s, forecast_s
            ),
        }

    def _serve_capacity(self, payload: Dict, seed: int, t0: float) -> Dict:
        """Capacity planning: replay a whole trace (tomorrow's diurnal
        load) against today's snapshot on the fork, through the sim
        tier's TraceDriver, and report SLO risk. Today's restored gangs
        stay resident for the whole replay (conservative: current load
        never departs), so the answer is "can tomorrow's load land ON
        TOP of today's"."""
        from ..sim.driver import TraceDriver

        trace = dict(payload["capacityTrace"])
        # Namespace the trace's gang names away from today's restored
        # gangs: trace generators reuse g0..gN, and a submit whose uid
        # collides with a restored BOUND pod is an admission error, not
        # tomorrow's load.
        events = []
        for ev in trace.get("events") or []:
            if ev.get("kind") == "submit":
                gang = dict(ev.get("gang") or {})
                gang["name"] = f"wfcap-{gang.get('name')}"
                ev = dict(ev, gang=gang)
            events.append(ev)
        trace["events"] = events
        slo_wait_s = float(payload.get("sloWaitS") or 600.0)
        tr = self.sched.tracer.trace("whatif", force=True, mode="capacity")
        with tr.span("forkBuild"):
            fork = self.build_fork(seed)
        fork_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        driver = TraceDriver(
            self.sched.config, scheduler=fork.sched, prepare_nodes=False
        )
        with self.shadow_section():
            with tr.span(
                "horizonReplay", events=len(trace.get("events") or [])
            ):
                report = driver.run(trace)
        forecast_s = time.perf_counter() - t1
        tr.finish()
        q = report["quotaSatisfaction"]
        counts = report["counts"]
        self.forecast_count += 1
        self.last_forecast_s = fork_s + forecast_s
        return {
            "mode": "capacity",
            "sloWaitS": slo_wait_s,
            "sloRisk": {
                # Guaranteed demand that missed entirely, plus demand
                # that landed but waited past the SLO.
                "unboundGuaranteed": (
                    q["submittedGuaranteed"] - q["boundGuaranteed"]
                ),
                "quotaSatisfaction": q["fraction"],
                "queueWaitP99S": q["queueWaitP99S"],
                "p99OverSlo": q["queueWaitP99S"] > slo_wait_s,
                "waitingAtEnd": counts["waitingAtEnd"],
            },
            "counts": counts,
            "preemption": report["preemption"],
            "fragmentation": (report.get("fragmentation") or {}).get(
                "endFreeSlices"
            ),
            "meta": self._meta(
                fork, len(trace.get("events") or []),
                float(trace.get("shape", {}).get("durationS") or 0.0),
                fork_s, forecast_s,
            ),
        }

    def _meta(self, fork, n_events, duration_s, fork_s, forecast_s) -> Dict:
        """The run-varying section of a reply (wall costs, staleness) —
        everything DELIBERATELY excluded from the deterministic
        forecasts list."""
        return {
            "epochTotal": self.sched.core.epoch_total(),
            "forkPods": fork.pod_count,
            "horizonEvents": n_events,
            "confidenceHorizonS": round(duration_s, 3),
            "forkMs": round(fork_s * 1e3, 3),
            "forecastMs": round(forecast_s * 1e3, 3),
            # How stale the ANSWER is by the time the caller reads it:
            # the age of the fork the forecast ran against (live state
            # kept moving while the shadow replayed).
            "stalenessS": (
                round(time.monotonic() - self.last_fork_at, 3)
                if self.last_fork_at is not None
                else 0.0
            ),
        }

    def metrics_snapshot(self) -> Dict:
        out = dict(WHATIF_EMPTY_METRICS)
        out.update(
            {
                "whatifForecastCount": self.forecast_count,
                "whatifForecastGangCount": self.forecast_gang_count,
                "whatifForkCount": self.fork_count,
                "whatifAuditViolationCount": self.audit_violations,
                "whatifForkPodCount": self.last_fork_pods,
                "whatifForkAgeSeconds": (
                    round(time.monotonic() - self.last_fork_at, 3)
                    if self.last_fork_at is not None
                    else -1.0
                ),
                "whatifForecastSeconds": round(self.last_forecast_s, 6),
            }
        )
        return out


# ------------------------------------------------------------------ #
# Sim-tier integration (TraceDriver's mid-trace forecast sample)
# ------------------------------------------------------------------ #


def sim_sample(
    driver,
    now: float,
    departures: List[Tuple[float, int, str]],
    waiting_gangs,
    verify_deterministic: bool = False,
) -> Dict:
    """Forecast the sim driver's CURRENT waiting queue against the known
    departure horizon — the bench's forecast-vs-actual instrument
    (HIVED_BENCH_WHATIF). ``departures`` is the driver's future-departure
    heap (absolute trace times); the horizon replayed on the fork is
    exactly those departures, shifted to be relative to ``now`` — future
    SUBMITS are deliberately excluded (the scheduler cannot know them;
    doc/hot-path.md records the resulting error as the honest null).

    Returns {"t", "forecasts", "meta", "deterministic"}; with
    ``verify_deterministic`` the whole forecast runs twice on two
    independent forks and the forecast lists are asserted identical."""
    plane = driver.sched.whatif
    events = [
        {
            "t": max(0.0, t - now),
            "kind": "depart",
            "group": gname,
            # The seq tiebreak keeps same-instant departures in the
            # driver's own deterministic pop order.
            "seq": seq,
        }
        for t, seq, gname in sorted(departures)
    ]
    duration_s = max([e["t"] for e in events], default=0.0)

    def once() -> Tuple[List[Dict], Dict]:
        tr = plane.sched.tracer.trace("whatif", force=True, mode="sim")
        t_fork = time.perf_counter()
        with tr.span("forkBuild"):
            fork = plane.build_fork(seed=0)
        fork_s = time.perf_counter() - t_fork
        gangs = []
        for g in waiting_gangs:
            pods = g.make_pods()
            gangs.append(
                _ForecastGang(g.name, g.vc, g.priority, pods)
            )
        t0 = time.perf_counter()
        with tracing.use(tr):
            with tr.span("horizonReplay", events=len(events)):
                forecasts = plane.run_forecast(
                    fork, gangs, events, duration_s
                )
        dt = time.perf_counter() - t0
        meta = plane._meta(fork, len(events), duration_s, fork_s, dt)
        tr.finish(gangs=len(forecasts))
        return forecasts, meta

    forecasts, meta = once()
    deterministic = None
    if verify_deterministic:
        again, _ = once()
        deterministic = again == forecasts
        if not deterministic:
            common.log.error(
                "whatif forecast NOT deterministic across repeated forks "
                "at one snapshot epoch"
            )
    plane.forecast_count += 1
    plane.forecast_gang_count += len(forecasts)
    return {
        "t": now,
        "forecasts": forecasts,
        "meta": meta,
        "deterministic": deterministic,
    }
