"""Live invariant auditor: the chaos harness's structural invariants with
production teeth (the black-box plane, doc/observability.md).

``audit_invariants`` is THE implementation of invariants 1-7 over a live
core — lifted out of ``tests/chaos.py`` (which imports it back, so the
harness and the production path can never drift). The chaos harness runs
it after every seeded event and *asserts*; production cannot afford an
assert, so :class:`LiveAuditor` runs the same function event-clocked at a
knob'd cadence (``auditIntervalTicks``; ``HIVED_LIVE_AUDIT=0`` hatch)
under a brief global section and **degrades gracefully**: a violation is
counted (``hived_audit_violations_total``), journaled into the decision
journal, and answered by an auto-dump of the whole black-box bundle —
flight-recorder window + decisions + traces + metrics — to
``HIVED_AUDIT_ARTIFACT_DIR``, while the scheduler keeps serving. The
sensitivity meta-test (tests/test_flight_recorder.py) proves injected
corruption is caught within one cadence and that a no-op'd auditor is
itself caught.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterator, Set

from .. import common
from ..algorithm.cell import (
    Cell,
    CellState,
    FREE_PRIORITY,
    LOWEST_LEVEL,
    MIN_GUARANTEED_PRIORITY,
    PhysicalCell,
)
from ..algorithm.core import in_free_cell_list
from ..algorithm.group import GroupState

# Event-clock cadence hatches. HIVED_LIVE_AUDIT=0 disables the live
# auditor entirely; HIVED_AUDIT_INTERVAL_TICKS overrides the config
# cadence (hack/soak.sh --audit sets =1 so every chaos verb is audited
# by BOTH the harness and the production path — the double-audit).
LIVE_AUDIT_ENV = "HIVED_LIVE_AUDIT"
AUDIT_INTERVAL_ENV = "HIVED_AUDIT_INTERVAL_TICKS"
AUDIT_ARTIFACT_DIR_ENV = "HIVED_AUDIT_ARTIFACT_DIR"


def _leaves(c: Cell) -> Iterator[PhysicalCell]:
    if not c.children:
        assert isinstance(c, PhysicalCell)
        yield c
        return
    for child in c.children:
        yield from _leaves(child)


def _count_at_level(c: Cell, level: int) -> int:
    if c.level == level:
        return 1
    if c.level < level or not c.children:
        return 0
    return sum(_count_at_level(child, level) for child in c.children)


def audit_invariants(sched, ctx: str = "") -> None:
    """Structural invariants over the live core; raises AssertionError with
    ``ctx`` on any violation. Cheap enough to run after every chaos event
    (the harness) and at the live cadence (LiveAuditor)."""
    core = sched.core
    for chain, ccl in core.full_cell_list.items():
        top = ccl.top_level
        # --- invariant 1a: the free list partitions the chain ------------- #
        derived = {l: 0 for l in range(LOWEST_LEVEL, top + 1)}
        covered: Set[str] = set()
        for level in range(LOWEST_LEVEL, top + 1):
            for c in core.free_cell_list[chain][level]:
                assert c.level == level, (ctx, chain, level, c.address)
                for l in range(LOWEST_LEVEL, level + 1):
                    derived[l] += _count_at_level(c, l)
                for leaf in _leaves(c):
                    assert leaf.address not in covered, (
                        ctx, chain, "free lists overlap", leaf.address,
                    )
                    covered.add(leaf.address)
                    # Invariant 5 (reservation conservation, half 1): no
                    # cell is both in the free lists and Reserved/Reserving
                    # — a reservation always allocates its preassigned cell
                    # out of the free lists. A free-covered USED leaf is
                    # legal only for opportunistic occupancy (that is how
                    # preemption victims arise).
                    assert leaf.state not in (
                        CellState.RESERVING, CellState.RESERVED,
                    ), (ctx, chain, "reserved cell in free list", leaf.address)
                    if leaf.state == CellState.USED:
                        assert leaf.priority < MIN_GUARANTEED_PRIORITY, (
                            ctx, chain, "guaranteed allocation in free list",
                            leaf.address, leaf.priority,
                        )
        for l in range(LOWEST_LEVEL, top + 1):
            assert core.total_left_cell_num[chain].get(l, 0) == derived[l], (
                ctx, chain, l, "totalLeft != cells derivable from free list",
                core.total_left_cell_num[chain].get(l, 0), derived[l],
            )
        # --- invariant 1b: per-leaf state machine ------------------------- #
        # --- + invariant 5 (reservation conservation, half 2): the leaf    #
        #     reservation pointers and the Reserving/Reserved states agree  #
        for leaf in ccl[LOWEST_LEVEL]:
            assert isinstance(leaf, PhysicalCell)
            if leaf.state == CellState.USED:
                assert leaf.using_group is not None, (ctx, leaf.address)
            if leaf.using_group is not None:
                assert leaf.state in (CellState.USED, CellState.RESERVING), (
                    ctx, leaf.address, leaf.state,
                )
            if leaf.state == CellState.FREE:
                assert leaf.using_group is None, (ctx, leaf.address)
                assert leaf.priority == FREE_PRIORITY, (
                    ctx, leaf.address, leaf.priority,
                )
            reserved = leaf.state in (CellState.RESERVING, CellState.RESERVED)
            assert reserved == (leaf.reserving_or_reserved_group is not None), (
                ctx, leaf.address, leaf.state,
                "reservation pointer and state disagree",
            )
            if leaf.state == CellState.RESERVED:
                assert leaf.using_group is None, (ctx, leaf.address)
            if leaf.state == CellState.RESERVING:
                assert leaf.using_group is not None, (ctx, leaf.address)
            if reserved:
                g = leaf.reserving_or_reserved_group
                assert g.state == GroupState.PREEMPTING, (
                    ctx, leaf.address, g.name, g.state,
                )
                assert any(
                    leaf is pl
                    for rows in g.physical_placement.values()
                    for row in rows
                    for pl in row
                ), (ctx, leaf.address, g.name,
                    "reserved leaf not in its preemptor's placement")
        # --- bad-free entries are actually bad and actually free ---------- #
        for level in range(LOWEST_LEVEL, top + 1):
            for c in core.bad_free_cells[chain][level]:
                assert isinstance(c, PhysicalCell)
                assert not c.healthy, (ctx, chain, level, c.address)
                assert in_free_cell_list(c), (ctx, chain, level, c.address)

    # --- invariant 2: doomed-bad-cell counter consistency ----------------- #
    doomed_sum: Dict[str, Dict[int, int]] = {}
    for vcn, per_chain in core.vc_doomed_bad_cells.items():
        for chain, ccl in per_chain.items():
            for level, cl in ccl.levels.items():
                if len(cl) == 0:
                    continue
                doomed_sum.setdefault(chain, {})
                doomed_sum[chain][level] = doomed_sum[chain].get(level, 0) + len(cl)
                for c in cl:
                    assert isinstance(c, PhysicalCell)
                    assert c.virtual_cell is not None, (ctx, vcn, c.address)
                    assert c.virtual_cell.vc == vcn, (ctx, vcn, c.address)
    for chain, per_level in core.all_vc_doomed_bad_cell_num.items():
        for level, n in per_level.items():
            assert n >= 0, (ctx, chain, level, n)
            assert doomed_sum.get(chain, {}).get(level, 0) == n, (
                ctx, chain, level, "doomed counter mismatch",
                doomed_sum.get(chain, {}).get(level, 0), n,
            )

    # --- VC free-quota ledgers sum to the global ledger ------------------- #
    vc_sum: Dict[str, Dict[int, int]] = {}
    for vcn, per_chain in core.vc_free_cell_num.items():
        for chain, per_level in per_chain.items():
            for level, n in per_level.items():
                vc_sum.setdefault(chain, {})
                vc_sum[chain][level] = vc_sum[chain].get(level, 0) + n
    for chain in set(vc_sum) | set(core.all_vc_free_cell_num):
        levels = set(vc_sum.get(chain, {})) | set(
            core.all_vc_free_cell_num.get(chain, {})
        )
        for level in levels:
            assert vc_sum.get(chain, {}).get(level, 0) == (
                core.all_vc_free_cell_num.get(chain, {}).get(level, 0)
            ), (ctx, chain, level, "vcFree sum != allVCFree")

    # --- invariant 7 (health consistency, structural half): leaf badness   #
    #     and drains match the core's applied records, badness propagates   #
    #     up the cell tree exactly (a cell is healthy iff all children      #
    #     are), bound virtual mirrors agree, and the incremental            #
    #     unusable-leaf counters equal the subtree truth                    #
    for chain, ccl in core.full_cell_list.items():
        top = ccl.top_level
        for leaf in ccl[LOWEST_LEVEL]:
            assert isinstance(leaf, PhysicalCell)
            node = leaf.nodes[0]
            expect_bad = node in core.bad_nodes or any(
                i in core.bad_chips.get(node, ())
                for i in leaf.leaf_cell_indices
            )
            assert leaf.healthy == (not expect_bad), (
                ctx, leaf.address, "leaf health != applied bad records",
            )
            expect_drain = any(
                i in core.draining_chips.get(node, ())
                for i in leaf.leaf_cell_indices
            )
            assert leaf.draining == expect_drain, (
                ctx, leaf.address, "leaf drain != applied drain records",
            )
        for level in range(LOWEST_LEVEL, top + 1):
            for c in ccl[level]:
                assert isinstance(c, PhysicalCell)
                if c.children:
                    assert c.healthy == all(
                        ch.healthy for ch in c.children
                    ), (ctx, c.address, "tree health propagation broken")
                derived_unusable = sum(
                    1
                    for leaf in _leaves(c)
                    if (not leaf.healthy) or leaf.draining
                )
                assert c.unusable_leaf_num == derived_unusable, (
                    ctx, c.address, "unusable-leaf counter drift",
                    c.unusable_leaf_num, derived_unusable,
                )
                if c.virtual_cell is not None:
                    assert c.virtual_cell.healthy == c.healthy, (
                        ctx, c.address, "bound virtual health mirror broken",
                    )

    # --- allocated groups reference live, non-free cells ------------------ #
    # --- + invariant 5 (reservation conservation, group side): a           #
    #     PREEMPTING group's cells are exactly Reserving/Reserved and point #
    #     back at it; a BeingPreempted group's cells are Used or Reserving  #
    for g in core.affinity_groups.values():
        for rows in g.physical_placement.values():
            for row in rows:
                for leaf in row:
                    if leaf is None:
                        continue
                    assert isinstance(leaf, PhysicalCell)
                    assert leaf.state != CellState.FREE, (
                        ctx, g.name, leaf.address,
                    )
                    if g.state == GroupState.PREEMPTING:
                        assert leaf.state in (
                            CellState.RESERVING, CellState.RESERVED,
                        ), (ctx, g.name, leaf.address, leaf.state)
                        assert leaf.reserving_or_reserved_group is g, (
                            ctx, g.name, leaf.address,
                        )
                    elif g.state == GroupState.BEING_PREEMPTED:
                        assert leaf.state in (
                            CellState.USED, CellState.RESERVING,
                        ), (ctx, g.name, leaf.address, leaf.state)


class LiveAuditor:
    """The always-on production half: ticks on the scheduler's mutating
    verbs, runs :func:`audit_invariants` every ``interval_ticks`` under a
    brief global section, and degrades gracefully on violation (count +
    journal + artifact dump — NEVER an assert into the serving path).

    Thread-safety: ``tick`` is called at verb exit from request threads;
    the counter increment rides the GIL and the audit itself serializes
    on the scheduler's global guard. A lost tick under a race only delays
    one audit by one event — acceptable for a cadence knob."""

    def __init__(self, sched, interval_ticks: int):
        self.sched = sched
        env = os.environ.get(AUDIT_INTERVAL_ENV, "").strip()
        if env:
            try:
                interval_ticks = int(env)
            except ValueError:
                pass
        self.interval_ticks = max(1, int(interval_ticks))
        self.ticks = 0
        self.audit_runs = 0
        self.violation_count = 0
        self.last_violation: str = ""
        self.last_artifact: str = ""

    # -- the event clock ------------------------------------------------ #

    def tick(self) -> None:
        """One mutating verb completed (called OUTSIDE every lock, from
        the framework's top-level verb exits only — never from paths that
        may hold a chain section, see framework._blackbox_exit)."""
        self.ticks += 1
        if self.ticks % self.interval_ticks == 0:
            self.run_audit(f"cadence tick={self.ticks}")

    def run_audit(self, ctx: str = "manual") -> bool:
        """One audit pass under the global section. Returns True when the
        invariants held. A violation is counted, journaled, and answered
        by the artifact dump; any OTHER failure (an audit crash on a
        half-built core) logs and counts as a run, never a violation —
        the auditor must not invent corruption."""
        sched = self.sched
        if getattr(sched, "_in_recovery", False):
            return True  # a half-replayed view is not auditable state
        self.audit_runs += 1
        try:
            with sched._lock:
                audit_invariants(sched, f"live-audit {ctx}")
            return True
        except AssertionError as e:
            self.violation_count += 1
            detail = str(e.args[0] if len(e.args) == 1 else e.args)
            self.last_violation = detail[:2000]
            common.log.error(
                "LIVE AUDIT VIOLATION #%d (%s): %s — scheduler keeps "
                "serving; black-box bundle dumping",
                self.violation_count, ctx, self.last_violation,
            )
            self._journal(ctx, detail)
            try:
                self.last_artifact = self.dump_artifact(ctx, detail)
            except Exception:  # noqa: BLE001 — the dump must never raise
                common.log.exception("audit artifact dump failed")
            return False
        except Exception as e:  # noqa: BLE001
            common.log.warning("live audit pass crashed (not counted as a "
                               "violation): %s", e)
            return True

    def _journal(self, ctx: str, detail: str) -> None:
        """A violation is a decision too: one journal record under the
        synthetic pod key ``_audit`` so ``/v1/inspect/decisions`` shows
        it inline with the attempts that led up to it."""
        try:
            rec = self.sched.decisions.begin("_audit", "_audit", "audit")
            rec.verdict_error(f"invariant violation ({ctx}): {detail[:500]}")
            self.sched.decisions.commit(rec)
        except Exception:  # noqa: BLE001 — diagnostics must never raise
            pass

    def dump_artifact(self, ctx: str, detail: str) -> str:
        """The black-box bundle: flight-recorder window + decision
        journal + trace ring + metrics, one JSON file per violation
        under HIVED_AUDIT_ARTIFACT_DIR (default $TMPDIR/hived-audit)."""
        import tempfile

        out_dir = os.environ.get(AUDIT_ARTIFACT_DIR_ENV) or os.path.join(
            tempfile.gettempdir(), "hived-audit"
        )
        os.makedirs(out_dir, exist_ok=True)
        sched = self.sched
        recorder = getattr(sched, "recorder", None)
        payload = {
            "context": ctx,
            "violation": detail,
            "violationCount": self.violation_count,
            "auditRuns": self.audit_runs,
            "wallTime": time.time(),
            "decisions": sched.decisions.snapshot(),
            "traces": sched.tracer.snapshot(),
            "metrics": sched.get_metrics(),
            "flightRecording": (
                recorder.recording() if recorder is not None else None
            ),
        }
        path = os.path.join(
            out_dir,
            f"audit-violation-{self.violation_count}-{os.getpid()}.json",
        )
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=str)
        common.log.error("black-box bundle dumped to %s", path)
        return path

    def metrics_snapshot(self) -> Dict:
        return {
            "auditRunCount": self.audit_runs,
            "auditViolationCount": self.violation_count,
        }
