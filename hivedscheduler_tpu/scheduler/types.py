"""Internal contracts: pod/node models, pod state machine, schedule results.

Python equivalent of the reference's ``pkg/internal`` (types.go:34-236,
utils.go:108-290). The K8s objects are modeled as plain dataclasses so the
whole algorithm layer is a hermetic, simulation-testable state machine — the
same property the reference's test suite exploits
(hived_algorithm_test.go:41-64).
"""

from __future__ import annotations

import enum
import functools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .. import common
from ..api import constants, types as api


@dataclass
class Pod:
    """The slice of a K8s Pod the scheduler needs
    (reference: core.Pod fields used across pkg/internal/utils.go)."""

    name: str
    namespace: str = "default"
    uid: str = ""
    annotations: Dict[str, str] = field(default_factory=dict)
    node_name: str = ""  # spec.nodeName; non-empty means bound
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    # container resource limits (for the scheduling-enable gate)
    resource_limits: Dict[str, int] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.uid}({self.namespace}/{self.name})"


@dataclass
class Node:
    """The slice of a K8s Node the scheduler needs."""

    name: str
    unschedulable: bool = False
    ready: bool = True
    # Health-plane inputs: the device-health / drain annotations and the
    # node conditions (type -> status=="True"), parsed by scheduler.health.
    annotations: Dict[str, str] = field(default_factory=dict)
    conditions: Dict[str, bool] = field(default_factory=dict)


def is_completed(pod: Pod) -> bool:
    """(reference: internal/utils.go:108-111)"""
    return pod.phase in ("Succeeded", "Failed")


def is_live(pod: Pod) -> bool:
    return not is_completed(pod)


def is_hived_enabled(pod: Pod) -> bool:
    """The extended-resource gate: at least one container sets our resource
    limit positive (reference: internal/utils.go:115-140)."""
    return pod.resource_limits.get(constants.RESOURCE_NAME_POD_SCHEDULING_ENABLE, 0) > 0


def is_interested(pod: Pod) -> bool:
    """(reference: internal/utils.go:142-147)"""
    return is_live(pod) and is_hived_enabled(pod)


def is_bound(pod: Pod) -> bool:
    """(reference: internal/utils.go:149-153)"""
    return pod.node_name != "" and is_live(pod)


def is_unbound(pod: Pod) -> bool:
    return pod.node_name == "" and is_live(pod)


def is_node_healthy(node: Node) -> bool:
    """Schedulable and Ready (reference: internal/utils.go:160-170)."""
    return not node.unschedulable and node.ready


def apply_node_fault_event(old: Node, ev: Dict) -> Optional[Node]:
    """The chaos/sim/what-if fault vocabulary applied to one Node: the
    NEW Node object the informer would deliver for ``node_flip``,
    ``chip_fault``/``chip_heal``, or ``drain_toggle`` (None for unknown
    kinds). The ONE implementation shared by the sim driver
    (index-resolved nodes) and the what-if plane's horizon replay
    (name-resolved), so the vocabulary cannot drift between them."""
    annotations = dict(old.annotations)
    ready = old.ready
    kind = str(ev.get("kind") or "")
    if kind == "node_flip":
        ready = ev.get("to", "down") == "up"
    elif kind in ("chip_fault", "chip_heal"):
        bad = set(
            x
            for x in annotations.get(
                constants.ANNOTATION_NODE_DEVICE_HEALTH, ""
            ).split(",")
            if x
        )
        chip = str(ev.get("chip", 0))
        if kind == "chip_fault":
            bad.add(chip)
        else:
            bad.discard(chip)
        if bad:
            annotations[constants.ANNOTATION_NODE_DEVICE_HEALTH] = (
                ",".join(sorted(bad))
            )
        else:
            annotations.pop(constants.ANNOTATION_NODE_DEVICE_HEALTH, None)
    elif kind == "drain_toggle":
        if ev.get("on"):
            annotations[constants.ANNOTATION_NODE_DRAIN] = "*"
        else:
            annotations.pop(constants.ANNOTATION_NODE_DRAIN, None)
    else:
        return None
    return Node(name=old.name, ready=ready, annotations=annotations)


class SchedulingPhase(str, enum.Enum):
    """(reference: internal/types.go:102-114)"""

    # Called from the filter route: suggested nodes fit the pod without
    # preempting anyone.
    FILTERING = "Filtering"
    # Called from the preempt route: suggested nodes fit the pod after
    # preempting all lower-priority pods.
    PREEMPTING = "Preempting"


class PodState(str, enum.Enum):
    """Pod states tracked by the scheduler framework
    (reference: internal/types.go:154-194)."""

    WAITING = "Waiting"
    PREEMPTING = "Preempting"
    BINDING = "Binding"
    BOUND = "Bound"


def is_allocated_state(state: PodState) -> bool:
    return state in (PodState.BINDING, PodState.BOUND)


@dataclass
class PodWaitInfo:
    """(reference: internal/types.go:198-201)"""

    reason: str = ""


@dataclass
class PodPreemptInfo:
    """Victim pods for the current preemptor
    (reference: internal/types.go:204-216)."""

    victim_pods: List[Pod] = field(default_factory=list)


@dataclass
class PodScheduleResult:
    """Exactly one of the three fields is set
    (reference: internal/types.go:116-136)."""

    pod_wait_info: Optional[PodWaitInfo] = None
    pod_preempt_info: Optional[PodPreemptInfo] = None
    pod_bind_info: Optional[api.PodBindInfo] = None
    # Batched-admission pass-through (doc/hot-path.md): the pod's slot
    # index inside its group's bind info, recorded when pod_bind_info is
    # generated so the assume-bind path can hand the already-decoded
    # decision straight back to core.add_allocated_pod instead of paying
    # a bind-info decode + O(gang) index scan per pod of the gang.
    pod_index: Optional[int] = None


@dataclass
class PodScheduleStatus:
    """Per-pod tracking record in the framework
    (reference: internal/types.go:139-152)."""

    pod: Pod
    pod_state: PodState
    pod_bind_attempts: int = 0
    pod_schedule_result: Optional[PodScheduleResult] = None


@dataclass
class QuarantineRecord:
    """A bound pod whose recovery replay failed (corrupt bind-info, cells
    absent from the current config). The pod is parked here — visible via
    /v1/inspect/quarantine — instead of aborting recovery; its cells are
    NOT charged to the scheduling view (no reference analog: the reference
    panics out of createAllocatedAffinityGroup on the same inputs)."""

    pod: Pod
    reason: str
    quarantined_at: str  # RFC 3339 UTC

    def to_dict(self) -> Dict[str, Any]:
        return {
            "podKey": self.pod.key,
            "podName": self.pod.name,
            "podNamespace": self.pod.namespace,
            "podUid": self.pod.uid,
            "node": self.pod.node_name,
            "reason": self.reason,
            "quarantinedAt": self.quarantined_at,
        }


def new_binding_pod(pod: Pod, bind_info: api.PodBindInfo) -> Pod:
    """A copy of the pod with the binding decision applied: node set, the
    isolation + bind-info + TPU env annotations attached
    (reference: internal/utils.go:172-186; the TPU env block replaces the
    reference's single NVIDIA_VISIBLE_DEVICES-style isolation var)."""
    from ..tpu import env as tpu_env  # late import: tpu depends on api only

    annotations = dict(pod.annotations)
    annotations[constants.ANNOTATION_POD_LEAF_CELL_ISOLATION] = (
        common.to_indices_string(bind_info.leaf_cell_isolation)
    )
    # Compact JSON (valid YAML, parsed at C speed on replay): bind-info
    # serialization+parse happens per pod per filter round and dominates
    # large-gang latency with the generic YAML codec.
    annotations[constants.ANNOTATION_POD_BIND_INFO] = common.to_json(
        bind_info.to_dict()
    )
    annotations[constants.ANNOTATION_POD_TPU_ENV] = common.to_yaml_fast(
        tpu_env.pod_tpu_env(bind_info)
    )
    return Pod(
        name=pod.name,
        namespace=pod.namespace,
        uid=pod.uid,
        annotations=annotations,
        node_name=bind_info.node,
        phase=pod.phase,
        resource_limits=dict(pod.resource_limits),
    )


def _extract_bind_shaped_annotation(pod: Pod, key: str) -> api.PodBindInfo:
    """Decode a PodBindInfo-shaped annotation. Cached parse: the
    group-replay paths re-read the same annotation many times per
    scheduling round; from_dict copies every field, so sharing the parsed
    dict is safe."""
    annotation = pod.annotations.get(key, "")
    if not annotation:
        raise api.bad_request(
            f"Pod does not contain or contains empty annotation: {key}"
        )
    return api.PodBindInfo.from_dict(common.from_yaml_cached(annotation) or {})


def extract_pod_bind_info(allocated_pod: Pod) -> api.PodBindInfo:
    """(reference: internal/utils.go:200-213; trusted input, assert-style)"""
    return _extract_bind_shaped_annotation(
        allocated_pod, constants.ANNOTATION_POD_BIND_INFO
    )


def extract_pod_preempt_info(allocated_pod: Pod) -> api.PodBindInfo:
    """Decode the reserved-placement annotation a preempting pod carries
    (same PodBindInfo shape as the bind-info annotation; ``node`` and
    ``leaf_cell_isolation`` are unused — the pod is not bound). Raises the
    same user error as :func:`extract_pod_bind_info` when absent/corrupt."""
    return _extract_bind_shaped_annotation(
        allocated_pod, constants.ANNOTATION_POD_PREEMPT_INFO
    )


def has_pod_preempt_info(pod: Pod) -> bool:
    return bool(pod.annotations.get(constants.ANNOTATION_POD_PREEMPT_INFO, ""))


def extract_pod_scheduling_spec(pod: Pod) -> api.PodSchedulingSpec:
    """Deserialize + default + validate the user-provided scheduling spec
    (reference: internal/utils.go:230-289). All failures are user errors
    (HTTP 400).

    The returned spec is CACHED per annotation string and shared when the
    annotation names an affinity group: every pod of a gang carries the
    identical annotation, and the same pod re-enters filter on each retry,
    so the DTO construction runs once per distinct spec on the hot path
    (doc/hot-path.md). Callers must treat the result as read-only. A spec
    WITHOUT an affinity group is never cached — its singleton-gang default
    is derived from the pod's own identity below."""
    annotation = pod.annotations.get(constants.ANNOTATION_POD_SCHEDULING_SPEC, "")
    if not annotation:
        raise api.bad_request(
            f"Pod annotation {constants.ANNOTATION_POD_SCHEDULING_SPEC}: "
            "Annotation does not exist or is empty"
        )
    spec = _parse_pod_scheduling_spec(annotation)
    if spec is not None:
        return spec

    # No affinity group in the annotation: build a per-pod spec (uncached —
    # the default group name is this pod's identity, so two pods with the
    # byte-identical annotation must NOT share it).
    spec = _decode_pod_scheduling_spec(annotation)
    spec.affinity_group = api.AffinityGroupSpec(
        name=f"{pod.namespace}/{pod.name}",
        members=[
            api.AffinityGroupMemberSpec(
                pod_number=1, leaf_cell_number=spec.leaf_cell_number
            )
        ],
    )
    _validate_pod_scheduling_spec(spec)
    return spec


def _decode_pod_scheduling_spec(annotation: str) -> api.PodSchedulingSpec:
    err_pfx = f"Pod annotation {constants.ANNOTATION_POD_SCHEDULING_SPEC}: "
    try:
        # from_dict defaults ignoreK8sSuggestedNodes to True when absent
        # (reference: api/types.go:86 `default:"true"`). Cached parse: the
        # YAML->dict decode is shared; from_dict copies every field so
        # sharing the parsed dict is safe.
        return api.PodSchedulingSpec.from_dict(
            common.from_yaml_cached(annotation) or {}
        )
    except api.WebServerError:
        raise
    except Exception as e:  # malformed YAML and the like
        raise api.bad_request(err_pfx + str(e))


@functools.lru_cache(maxsize=8192)
def _parse_pod_scheduling_spec(annotation: str) -> Optional[api.PodSchedulingSpec]:
    """Decode + validate, returning None when the spec has no affinity group
    (the pod-dependent singleton default cannot be cached). Exceptions are
    not cached by lru_cache: a malformed annotation re-raises its
    bad_request on every call, exactly as before."""
    spec = _decode_pod_scheduling_spec(annotation)
    if spec.affinity_group is None:
        return None
    _validate_pod_scheduling_spec(spec)
    return spec


def _validate_pod_scheduling_spec(spec: api.PodSchedulingSpec) -> None:
    err_pfx = f"Pod annotation {constants.ANNOTATION_POD_SCHEDULING_SPEC}: "
    # Validation (reference: internal/utils.go:253-287).
    if not spec.virtual_cluster:
        raise api.bad_request(err_pfx + "VirtualCluster is empty")
    if spec.priority < constants.OPPORTUNISTIC_PRIORITY:
        raise api.bad_request(
            err_pfx + f"Priority is less than {constants.OPPORTUNISTIC_PRIORITY}"
        )
    if spec.priority > constants.MAX_GUARANTEED_PRIORITY:
        raise api.bad_request(
            err_pfx + f"Priority is greater than {constants.MAX_GUARANTEED_PRIORITY}"
        )
    if spec.leaf_cell_number <= 0:
        raise api.bad_request(err_pfx + "LeafCellNumber is non-positive")
    if not spec.affinity_group.name:
        raise api.bad_request(err_pfx + "AffinityGroup.Name is empty")
    pod_in_group = False
    for member in spec.affinity_group.members:
        if member.pod_number <= 0:
            raise api.bad_request(
                err_pfx + "AffinityGroup.Members has non-positive PodNumber"
            )
        if member.leaf_cell_number <= 0:
            raise api.bad_request(
                err_pfx + "AffinityGroup.Members has non-positive LeafCellNumber"
            )
        if member.leaf_cell_number == spec.leaf_cell_number:
            pod_in_group = True
    if not pod_in_group:
        raise api.bad_request(
            err_pfx + "AffinityGroup.Members does not contains current Pod"
        )
