"""Benchmark: gang-schedule p50 latency (BASELINE.json headline metric).

Drives the production scheduling path (HivedScheduler.filter_routine — the
same code the HTTP extender calls, including assume-bind and bind-info
generation) over a simulated large TPU fleet: 4 v5p-64 cubes (64 hosts) +
8 v5e-16 slices (32 hosts) + 8 standalone v5e hosts, two VCs, with gang
sizes mixed 1/2/4/16-pod and steady job churn.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no numbers (BASELINE.md: "published": {}); this run
*establishes* the baseline, so vs_baseline is ours/target where target is
the 10 ms p50 budget implied by the reference's 5 s extender HTTP timeout
and its ~50 ms FIFO block knob (BASELINE.md) — lower is better.
"""

from __future__ import annotations

import gc
import json
import logging
import os
import socket
import statistics
import subprocess
import sys
import time

from hivedscheduler_tpu import common
from hivedscheduler_tpu.api import constants, extender as ei
from hivedscheduler_tpu.api.config import Config
from hivedscheduler_tpu.api.types import CellTypeSpec
from hivedscheduler_tpu.scheduler import tracing as hived_tracing
from hivedscheduler_tpu.scheduler.framework import HivedScheduler, NullKubeClient
from hivedscheduler_tpu.scheduler.types import Node, Pod
from hivedscheduler_tpu.tpu import topology

common.init_logging(logging.ERROR)

TARGET_P50_MS = 10.0

# Breadcrumb attached to any skipped model_perf stage: where the last
# complete on-chip measurements live (human-readable session log).
LAST_RECORDED_RUN = "example/logs/perf_tpu_round5.md"


def _load_artifact(model: str | None = None) -> dict | None:
    """Load a persisted on-chip measurement via THE writer's own path
    resolution (env override + per-model suffix; perf.artifact_path is
    the single owner of the naming rule). perf.py's module level is
    stdlib-only, so this import never drags the JAX stack into the bench
    process. None when absent/unreadable."""
    try:
        from hivedscheduler_tpu.models.perf import artifact_path

        with open(artifact_path(model)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ImportError):
        return None


def _skip(reason: str) -> dict:
    """A skipped model_perf stage still carries the last successful on-chip
    measurement *inline* (perf.persist_result writes it; provenance fields
    say which chip/commit/time produced it) — a dead TPU tunnel degrades the
    evidence from live to cached-with-provenance, never to a bare file-path
    breadcrumb."""
    out = {"skipped": reason, "last_recorded_run": LAST_RECORDED_RUN}
    measured = _load_artifact()
    if measured is not None:
        out["last_measured"] = measured
    return out


def _merge_carried(result: dict) -> dict:
    """A successful LIVE run benches the headline shape only (the optional
    stages are env-gated, and re-measuring the ~6-minute long-context sweep
    on every driver bench would risk the subprocess timeout) — so attach
    the persisted artifact's rows for any stage the live result lacks.
    The subprocess just persisted its own result with carry-forward, so
    the artifact is fresh and each carried stage's provenance names the
    run that really measured it. Only a HEALTHY on-TPU result qualifies:
    gluing chip-measured sweep rows onto a CPU-backend smoke run or a
    train_error result would claim evidence the run didn't produce (the
    XLA-fallback path refuses the same way)."""
    if (
        "skipped" in result  # _skip already embeds the whole artifact
        or result.get("backend") in (None, "cpu")
        or "train_error" in result
        # Same degraded-run refusals as persist_result: a kill-switch /
        # fallback XLA run (pallas_used false) or an untrustworthy timing
        # sync (mfu_rejected) must not wear flash-measured sweep rows.
        or not result.get("pallas_used")
        or "mfu_rejected" in result
    ):
        return result
    art = _load_artifact()
    if art is None:
        return result
    from hivedscheduler_tpu.models.perf import (
        CARRY_STAGES,
        attach_carried,
        stage_rows_clean,
    )

    for stage in CARRY_STAGES:
        # "Effectively missing" uses the writer's own cleaning rule: an
        # error-only live stage was dropped from the artifact by
        # persist_result, so the carried good rows belong here too — but
        # keep the live error visible instead of silently replacing it.
        if stage in result and stage_rows_clean(result[stage]) is None:
            result.setdefault("live_stage_errors", {})[stage] = (
                result.pop(stage)
            )
        if stage not in result and stage in art:
            attach_carried(result, art, stage)
    return result


def _attach_sizing(result: dict) -> dict:
    """Attach the persisted 800m sizing measurement (the largest
    single-chip AdamW-f32-master shape, doc/perf.md) to the model_perf
    stage output — live OR skipped: the live path benches the headline
    268m shape only, so the ≥0.8B evidence rides along from its own
    artifact, provenance included. Skipped when this run IS the 800m
    preset — the live result (or _skip's last_measured) already carries
    that shape."""
    if os.environ.get("HIVED_PERF_MODEL") == "800m":
        return result
    sizing = _load_artifact("800m")
    if sizing is not None:
        result["sizing_800m"] = sizing
    return result


# The fleet builder and pod factory moved to the sim tier (the bench and
# the warehouse-scale trace driver share one fleet shape); re-exported
# here so every existing call site and test keeps working.
from hivedscheduler_tpu.sim.fleet import build_config, make_pod  # noqa: E402


# (vc, leaf_type, pods, chips_per_pod)
GANG_SHAPES = [
    ("prod", "v5p-chip", 16, 4),     # whole v5p-64 gang
    ("prod", "v5e-chip", 4, 4),      # v5e-16 gang
    ("research", "v5p-chip", 4, 4),  # v5p-16 gang
    ("research", "v5e-chip", 4, 4),
    ("research", "v5e-chip", 1, 4),  # singleton host
    ("research", "v5e-chip", 1, 2),  # sub-host
]

# Pod-dense mix for the recovery-blackout stage: recovery cost scales with
# BOUND POD COUNT (one annotation replay each — the paper's motivating
# blackout is a 100k-pod fleet), so the blackout A/B packs the same fleet
# with many small pods instead of few large ones.
DENSE_GANG_SHAPES = [
    ("prod", "v5p-chip", 4, 1),
    ("prod", "v5e-chip", 2, 1),
    ("research", "v5p-chip", 4, 2),
    ("research", "v5e-chip", 2, 1),
    ("research", "v5e-chip", 1, 1),
    ("research", "v5e-chip", 1, 2),
]



def _drive_gangs(sched, schedule_pod, n_gangs, prefix="g", shapes=None):
    """Shared gang generator + churn loop for the latency stages: submit
    GANG_SHAPES-mix gangs (or ``shapes``), time each whole gang via
    ``schedule_pod`` (in-process or over the wire), and churn the oldest
    gangs when the cluster fills. Returns (latencies_ms, live,
    pods_scheduled)."""
    shapes = shapes or GANG_SHAPES
    lat, live, pods_scheduled = [], [], 0
    for g in range(n_gangs):
        vc, leaf_type, n_pods, chips = shapes[g % len(shapes)]
        gname = f"{prefix}{g}"
        group = {
            "name": gname,
            "members": [{"podNumber": n_pods, "leafCellNumber": chips}],
        }
        pods = [
            make_pod(f"{gname}-{i}", f"{gname}-u{i}", vc, 0, leaf_type,
                     chips, group)
            for i in range(n_pods)
        ]
        for p in pods:
            sched.add_pod(p)
        t0 = time.perf_counter()
        ok, bound = True, []
        for p in pods:
            if not schedule_pod(p):
                ok = False
                break
            bound.append(sched.pod_schedule_statuses[p.uid].pod)
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        if ok:
            lat.append(elapsed_ms)
            live.append((gname, bound))
            pods_scheduled += len(bound)
        else:
            # Cluster full: free the oldest gangs (job churn), drop this
            # gang's partial state.
            for p in pods:
                sched.delete_pod(p)
            for _, old in live[: max(1, len(live) // 3)]:
                for q in old:
                    sched.delete_pod(q)
            live = live[max(1, len(live) // 3):]
    return lat, live, pods_scheduled


def _percentiles(lat):
    p50 = statistics.median(lat)
    p99 = sorted(lat)[min(len(lat) - 1, int(0.99 * len(lat)))]
    return p50, p99


def _stage_meta(result: dict, hosts: int, t0: float) -> dict:
    """Artifact hygiene (ISSUE 9 satellite): every stage records the fleet
    size it ran at, the host's core count, and its own wall clock under
    the SAME keys, so fleet-scale trend lines are comparable across bench
    rounds without per-stage key archaeology. Call last, with the stage's
    start time."""
    result["hosts"] = hosts
    result["cpu_count"] = os.cpu_count()
    result["wall_s"] = round(time.perf_counter() - t0, 3)
    return result


def run(n_gangs: int = 120, config: Config | None = None,
        trace_sample: float | None = None):
    sched = HivedScheduler(
        config if config is not None else build_config(),
        kube_client=NullKubeClient(),
        trace_sample=trace_sample,
    )
    nodes = sched.core.configured_node_names()
    for n in nodes:
        sched.add_node(Node(name=n))

    def schedule_pod(p):
        r = sched.filter_routine(ei.ExtenderArgs(pod=p, node_names=nodes))
        return bool(r.node_names)

    lat, live, pods = _drive_gangs(sched, schedule_pod, n_gangs)
    p50, p99 = _percentiles(lat)
    # Sustained filter-path rate: every scheduled pod's filter call (incl.
    # assume-bind state updates) over the summed in-schedule time.
    pods_per_sec = pods / (sum(lat) / 1e3) if lat else 0.0
    return p50, p99, len(lat), sched, live, pods_per_sec


def smoke(n_gangs: int = 24) -> dict:
    """Scheduler-only smoke stage: gang-schedule p50, sustained pods/sec,
    the per-phase filter breakdown (lock-wait / core-schedule /
    leaf-cell search), and a one-rep tracing-on/off p50 delta at a small
    gang count — no HTTP, no recovery, no TPU/model stages. Env-gated in
    ``__main__`` via ``HIVED_BENCH_SMOKE=1`` (gang count override:
    ``HIVED_BENCH_SMOKE_GANGS``), and wired into tier-1 by
    tests/test_bench_smoke.py so a hot-path regression fails CI in seconds
    instead of surfacing in the full driver bench. (The driver-grade
    tracing gate is ``bench_tracing_ab`` at the 432-host fleet; the smoke
    delta is a wiring check, not a perf claim.)"""
    t0 = time.perf_counter()
    p50, p99, n, sched, live, pods_per_sec = run(
        n_gangs=n_gangs, trace_sample=hived_tracing.DEFAULT_SAMPLE
    )
    p50_off, *_ = run(n_gangs=n_gangs, trace_sample=0.0)
    m = sched.get_metrics()
    return _stage_meta({
        "gang_schedule_p50_ms": round(p50, 3),
        "gang_schedule_p99_ms": round(p99, 3),
        "gangs_scheduled": n,
        "pods_per_sec": round(pods_per_sec, 1),
        "filter_count": m["filterCount"],
        "phases": m["phases"],
        "tracing_delta": {
            "trace_sample": hived_tracing.DEFAULT_SAMPLE,
            "p50_on_ms": round(p50, 3),
            "p50_off_ms": round(p50_off, 3),
            "overhead_pct": round((p50 / p50_off - 1.0) * 100.0, 2)
            if p50_off
            else 0.0,
        },
    }, 104, t0)


def bench_tracing_ab(
    cubes: int = 16,
    slices: int = 40,
    solos: int = 16,
    n_gangs: int = 240,
    reps: int = 3,
) -> dict:
    """Tracing-overhead A/B at the 432-host fleet (ISSUE 6 acceptance):
    gang-schedule p50 with default-sampling tracing vs tracing disabled,
    interleaved reps (shared machine noise), medians. The acceptance gate
    is overhead ≤ 3% of p50; ``within_budget`` records the verdict in the
    BENCH artifact."""
    t0 = time.perf_counter()
    cfg = lambda: build_config(cubes, slices, solos)  # noqa: E731
    on_ms: list = []
    off_ms: list = []
    for _ in range(reps):
        off_ms.append(run(n_gangs=n_gangs, config=cfg(), trace_sample=0.0)[0])
        on_ms.append(
            run(
                n_gangs=n_gangs,
                config=cfg(),
                trace_sample=hived_tracing.DEFAULT_SAMPLE,
            )[0]
        )
    p50_on = statistics.median(on_ms)
    p50_off = statistics.median(off_ms)
    overhead_pct = (p50_on / p50_off - 1.0) * 100.0 if p50_off else 0.0
    return _stage_meta({
        "fleet_hosts": 16 * cubes + 4 * slices + solos,
        "gangs": n_gangs,
        "reps": reps,
        "trace_sample": hived_tracing.DEFAULT_SAMPLE,
        "p50_tracing_on_ms": round(p50_on, 3),
        "p50_tracing_off_ms": round(p50_off, 3),
        "overhead_pct": round(overhead_pct, 2),
        "budget_pct": 3.0,
        "within_budget": overhead_pct <= 3.0,
    }, 16 * cubes + 4 * slices + solos, t0)


def bench_audit(
    cubes: int = 16,
    slices: int = 40,
    solos: int = 16,
    n_gangs: int = 240,
    reps: int = 3,
    replay_hosts: int = 432,
    replay_gangs: int = 140,
    replay_seed: int = 3,
    frontend_shards: int = 2,
    frontend_families: int = 4,
    frontend_hosts_per_family: int = 108,
    frontend_reps: int = 3,
) -> dict:
    """Black-box plane acceptance stage (HIVED_BENCH_AUDIT=1;
    doc/hot-path.md "Black-box plane"): two parts.

    **Overhead A/B** — gang-schedule p50 at the 432-host fleet with the
    live invariant auditor and flight recorder at DEFAULT cadence vs both
    off, interleaved reps (shared-machine noise), medians, gated against
    the PR-6 ≤3% budget; auditor-only and recorder-only sides isolate
    each mechanism's share.

    **Capture→replay** (asserted, not just recorded) — a seeded burst
    trace with faults and preemption pressure runs through TraceDriver
    with the recorder armed; the captured window must contain at least
    one preemption and REPLAY FINGERPRINT-IDENTICALLY through the
    what-if-fork restore path (`--replay-recording`'s engine). This is
    the "a captured incident is a deterministic repro" acceptance."""
    from hivedscheduler_tpu.scheduler.recorder import (
        recording_fingerprint, replay_recording,
    )
    from hivedscheduler_tpu.sim.driver import (
        TraceDriver, build_fleet_config,
    )
    from hivedscheduler_tpu.sim.trace import TraceShape, generate_trace

    t0 = time.perf_counter()
    # The stage A/Bs the mechanisms via CONFIG knobs; ambient env
    # hatches (HIVED_FLIGHT_RECORDER=0 / HIVED_LIVE_AUDIT=0 /
    # HIVED_AUDIT_INTERVAL_TICKS) would silently blank a side — or
    # crash the capture below on a None recorder — so pin them for the
    # stage's duration and restore after.
    _saved_env = {
        k: os.environ.pop(k, None)
        for k in ("HIVED_FLIGHT_RECORDER", "HIVED_LIVE_AUDIT",
                  "HIVED_AUDIT_INTERVAL_TICKS")
    }
    try:
        result = _bench_audit_inner(
            cubes, slices, solos, n_gangs, reps,
            replay_hosts, replay_gangs, replay_seed, t0,
            TraceDriver, build_fleet_config, TraceShape, generate_trace,
            recording_fingerprint, replay_recording,
        )
        # Under worker processes the recorder captures at the FRONTEND
        # (workers run flight_recorder=False), so its cost lands on the
        # routing parent — the one vantage the in-process A/B above
        # cannot see. Same 3% budget, separate measurement.
        result["frontend_recorder_ab"] = _audit_frontend_recorder_ab(
            frontend_shards, frontend_families,
            frontend_hosts_per_family, frontend_reps,
        )
        result["wall_s"] = round(time.perf_counter() - t0, 2)
        return result
    finally:
        for k, v in _saved_env.items():
            if v is not None:
                os.environ[k] = v


def _bench_audit_inner(
    cubes, slices, solos, n_gangs, reps,
    replay_hosts, replay_gangs, replay_seed, t0,
    TraceDriver, build_fleet_config, TraceShape, generate_trace,
    recording_fingerprint, replay_recording,
) -> dict:

    def cfg(audit: bool, recorder: bool) -> Config:
        c = build_config(cubes, slices, solos)
        if not audit:
            c.audit_interval_ticks = 0
        if not recorder:
            c.flight_recorder_capacity = 0
        return c

    sides = {
        "off": (False, False),
        "audit_only": (True, False),
        "recorder_only": (False, True),
        "on": (True, True),
    }
    p50s: dict = {k: [] for k in sides}
    last_on_sched = None
    for _ in range(reps):
        for name, (audit, recorder) in sides.items():
            p50, _p99, _n, sched, _live, _pps = run(
                n_gangs=n_gangs, config=cfg(audit, recorder),
                trace_sample=0.0,
            )
            p50s[name].append(p50)
            if name == "on":
                last_on_sched = sched
    med = {k: statistics.median(v) for k, v in p50s.items()}
    overhead_pct = (
        (med["on"] / med["off"] - 1.0) * 100.0 if med["off"] else 0.0
    )
    on_metrics = (
        last_on_sched.get_metrics() if last_on_sched is not None else {}
    )

    # -- capture -> replay (asserted) --------------------------------- #
    shape = TraceShape(
        hosts=replay_hosts,
        gangs=replay_gangs,
        duration_s=1800.0,
        pattern="burst",
        burst_fraction=0.6,
        opportunistic_fraction=0.4,
        mean_runtime_s=700.0,
        fault_events=12,
    )
    trace = generate_trace(replay_seed, shape)
    config, actual_hosts = build_fleet_config(replay_hosts)
    config.flight_recorder_capacity = 1 << 18  # one window, whole run
    driver = TraceDriver(config)
    driver.sched.recorder.hosts = actual_hosts
    live_report = driver.run(trace)
    recording = driver.sched.recorder.recording()
    driver.close()
    assert live_report["counts"]["preemptionEvents"] >= 1, (
        "replay-acceptance trace produced no preemption; the repro "
        "claim would be untested", live_report["counts"],
    )
    assert live_report["counts"]["faultsApplied"] >= 1
    replay = replay_recording(recording, build_fleet_config(replay_hosts)[0])
    assert replay["identical"], (
        "flight recording did NOT replay fingerprint-identically",
        replay["liveFingerprint"], replay["replayFingerprint"],
    )

    return _stage_meta({
        "gangs": n_gangs,
        "reps": reps,
        "p50_off_ms": round(med["off"], 3),
        "p50_audit_only_ms": round(med["audit_only"], 3),
        "p50_recorder_only_ms": round(med["recorder_only"], 3),
        "p50_on_ms": round(med["on"], 3),
        "overhead_pct": round(overhead_pct, 2),
        "budget_pct": 3.0,
        "within_budget": overhead_pct <= 3.0,
        "audit_interval_ticks": 256,
        "audit_runs_on_side": on_metrics.get("auditRunCount", 0),
        "audit_violations": on_metrics.get("auditViolationCount", 0),
        "recorder_events_on_side": on_metrics.get(
            "flightRecorderEventCount", 0
        ),
        "replay": {
            "hosts": actual_hosts,
            "seed": replay_seed,
            "bound_gangs": live_report["counts"]["boundGangs"],
            "preemption_events": (
                live_report["counts"]["preemptionEvents"]
            ),
            "faults_applied": live_report["counts"]["faultsApplied"],
            "window_events": recording["meta"]["windowEvents"],
            "fingerprint": recording_fingerprint(recording),
            "identical": True,  # asserted above
        },
    }, 16 * cubes + 4 * slices + solos, t0)


def _audit_frontend_recorder_ab(
    n_shards: int = 2,
    families: int = 4,
    hosts_per_family: int = 108,
    reps: int = 3,
) -> dict:
    """Frontend flight-recorder A/B under procShards: fill-phase filter
    p50 through the JSON-bytes path with the recorder at its default
    capacity vs ``flight_recorder_capacity=0``, interleaved reps,
    medians. The in-process A/B in ``_bench_audit_inner`` measures the
    recorder inline with the core; this side measures it where the
    sharded deployment actually pays it — on the routing parent, racing
    the worker pipes."""
    from hivedscheduler_tpu.scheduler.shards import ShardedScheduler

    def build(recorder_on: bool):
        cfg = build_concurrent_config(families, hosts_per_family)
        if not recorder_on:
            cfg.flight_recorder_capacity = 0
        front = ShardedScheduler(
            cfg, kube_client=NullKubeClient(), n_shards=n_shards,
            transport="proc", auto_admit=True,
        )
        for n in front.configured_node_names():
            front.add_node(Node(name=n))
        fam_nodes = {
            fam: [
                n for n in front.configured_node_names()
                if n.startswith(f"cc{fam}-")
            ]
            for fam in range(families)
        }
        return front, fam_nodes

    fronts = {"on": build(True), "off": build(False)}
    assert fronts["on"][0].recorder is not None
    assert fronts["off"][0].recorder is None
    p50s: dict = {"on": [], "off": []}

    def one_fill(side: str, rep: str):
        front, fam_nodes = fronts[side]
        # Level the allocator debt between sides: the recorder side
        # allocates ring events, and on a small container a GC pause
        # inside one side's window would bill that side alone.
        gc.collect()
        lat: list = []
        bound: list = []
        for fam in range(families):
            load = _family_fill_load(
                fam, f"ab{side}{rep}", fam_nodes[fam],
                max(1, hosts_per_family // 4),
            )
            for pods, bodies in load:
                for p, body in zip(pods, bodies):
                    t1 = time.perf_counter()
                    r = json.loads(front.filter_raw(body))
                    lat.append((time.perf_counter() - t1) * 1000.0)
                    if r.get("NodeNames"):
                        bound.append(p)
        front.delete_pods(bound)
        return statistics.median(lat)

    try:
        # Unmeasured warmup fill per side: route cache, node-set ids,
        # and allocator warm state must not bill the first measured
        # side. Measured reps then alternate side order so machine
        # drift cancels instead of accumulating against one side.
        for side in fronts:
            one_fill(side, "warm")
        for rep in range(reps):
            order = ("on", "off") if rep % 2 == 0 else ("off", "on")
            for side in order:
                p50s[side].append(one_fill(side, f"r{rep}"))

        # Noise-resistant companion number: the hook itself,
        # micro-profiled in isolation on the parent (no worker round
        # trip to drown it in scheduling jitter). First-sight = full
        # pod construction per event (the fill-phase worst case);
        # memo-hit = the retry-storm steady state.
        front_on, fam_nodes_on = fronts["on"]
        rec = front_on.recorder
        prof = _family_fill_load(
            0, "hookprof", fam_nodes_on[0],
            max(1, hosts_per_family // 4),
        )
        reqs = [
            json.loads(b) for _pods, bodies in prof for b in bodies
        ]
        gc.collect()
        t1 = time.perf_counter()
        for d in reqs:
            rec.record_filter_wire(d, "placed")
        first_us = (time.perf_counter() - t1) / len(reqs) * 1e6
        t1 = time.perf_counter()
        for d in reqs:
            rec.record_filter_wire(d, "placed")
        hit_us = (time.perf_counter() - t1) / len(reqs) * 1e6
    finally:
        for front, _fn in fronts.values():
            front.close()
    med_on = statistics.median(p50s["on"])
    med_off = statistics.median(p50s["off"])
    overhead_pct = (med_on / med_off - 1.0) * 100.0 if med_off else 0.0
    return {
        "n_shards": n_shards,
        "families": families,
        "hosts_per_family": hosts_per_family,
        "reps": reps,
        "p50_recorder_on_ms": round(med_on, 3),
        "p50_recorder_off_ms": round(med_off, 3),
        "overhead_pct": round(overhead_pct, 2),
        "budget_pct": 3.0,
        "within_budget": overhead_pct <= 3.0,
        "hook_first_sight_us": round(first_us, 2),
        "hook_memo_hit_us": round(hit_us, 2),
        "hook_pct_of_p50": round(
            first_us / (med_on * 1000.0) * 100.0, 2
        ) if med_on else 0.0,
    }


def bench_preempt(sched, nodes, n_calls: int = 30) -> float:
    """p50 latency of the production preempt verb on the loaded cluster:
    a high-priority gang preempts, is re-probed (the extender re-enters
    the preempt verb for each preemptor pod every round while victims
    terminate — the path the epoch-gated victims cache serves), then
    cancels (shrunken suggested set) — commit, probe, and cancellation,
    the three expensive preemption paths."""
    lat = []
    victims_template = {n: {} for n in nodes}
    for i in range(n_calls):
        group = {
            "name": f"preemptor-{i}",
            "members": [{"podNumber": 4, "leafCellNumber": 4}],
        }
        pod = make_pod(
            f"preemptor-{i}-0", f"preemptor-{i}-u0", "prod", 100,
            "v5p-chip", 4, group,
        )
        sched.add_pod(pod)
        t0 = time.perf_counter()
        sched.preempt_routine(
            ei.ExtenderPreemptionArgs(
                pod=pod, node_name_to_meta_victims=dict(victims_template)
            )
        )
        # Re-probe the now-PREEMPTING gang (same candidate set).
        sched.preempt_routine(
            ei.ExtenderPreemptionArgs(
                pod=pod, node_name_to_meta_victims=dict(victims_template)
            )
        )
        # Cancel by rescheduling with an empty candidate set.
        sched.preempt_routine(
            ei.ExtenderPreemptionArgs(pod=pod, node_name_to_meta_victims={})
        )
        lat.append((time.perf_counter() - t0) * 1e3)
        sched.delete_pod(pod)
    return statistics.median(lat)


# ---------------------------------------------------------------------- #
# Concurrent filter stage (HIVED_BENCH_CONCURRENT=1): lock sharding A/B
# ---------------------------------------------------------------------- #


def build_concurrent_config(
    n_families: int, hosts_per_family: int, block_ms: int = 0
) -> Config:
    """A fleet of ``n_families`` hardware families, each its own leaf SKU
    and therefore its own cell chain and its own VC — the shape lock
    sharding is built for: filter calls of different families share no
    chain, so their critical sections interleave instead of queuing.

    ``block_ms`` sets the FIFO fairness knob (the reference blocks waiting
    pods ~50 ms INSIDE the scheduler lock, scheduler.go:567-571, to get
    better FIFO ordering): under the single lock that block stalls every
    family's scheduling; under sharding it stalls only the waiting pod's
    own chain — the concurrency win this stage measures."""
    cell_types: dict = {}
    physical = []
    vcs = {}
    for i in range(n_families):
        chip, host, slice_t = f"cc{i}-chip", f"cc{i}-host", f"cc{i}-slice"
        cell_types[host] = CellTypeSpec(
            child_cell_type=chip, child_cell_number=4, is_node_level=True
        )
        cell_types[slice_t] = CellTypeSpec(
            child_cell_type=host, child_cell_number=4
        )
        n_slices = max(1, hosts_per_family // 4)
        for s in range(n_slices):
            physical.append(
                topology.make_physical_cell(
                    slice_t,
                    [f"cc{i}-s{s}-w{j}" for j in range(4)],
                    cell_types,
                ).to_dict()
            )
        vcs[f"vc{i}"] = {
            "virtualCells": [{"cellType": slice_t, "cellNumber": n_slices}]
        }
    return Config.from_dict(
        {
            "physicalCluster": {
                "cellTypes": {
                    n: {
                        "childCellType": s.child_cell_type,
                        "childCellNumber": s.child_cell_number,
                        "isNodeLevel": s.is_node_level,
                    }
                    for n, s in cell_types.items()
                },
                "physicalCells": physical,
            },
            "virtualClusters": vcs,
            "waitingPodSchedulingBlockMilliSec": block_ms,
        }
    )


def _drive_family(sched, nodes, family, n_gangs):
    """One thread's load: churn gangs of one family's SKU through the
    production filter path (auto-admit: no informer add_pod events, so the
    loop's only global-order acquisitions are the churn deletes)."""
    live, pods_scheduled = [], 0
    chip = f"cc{family}-chip"
    vc = f"vc{family}"
    for g in range(n_gangs):
        n_pods = (1, 2, 4)[g % 3]
        gname = f"cc{family}-g{g}"
        group = {
            "name": gname,
            "members": [{"podNumber": n_pods, "leafCellNumber": 4}],
        }
        pods = [
            make_pod(f"{gname}-{i}", f"{gname}-u{i}", vc, 0, chip, 4, group)
            for i in range(n_pods)
        ]
        ok, bound = True, []
        for p in pods:
            r = sched.filter_routine(ei.ExtenderArgs(pod=p, node_names=nodes))
            if not r.node_names:
                ok = False
                break
            bound.append(sched.pod_schedule_statuses[p.uid].pod)
        if ok:
            live.append(bound)
            pods_scheduled += len(bound)
        else:
            for p in pods:
                sched.delete_pod(p)
            for old in live[: max(1, len(live) // 3)]:
                for q in old:
                    sched.delete_pod(q)
            live = live[max(1, len(live) // 3):]
    return pods_scheduled


def bench_concurrent(
    threads: int = 4,
    gangs_per_thread: int = 120,
    hosts_per_family: int = 16,
    block_ms: int = 20,
) -> dict:
    """Aggregate filter throughput with ``threads`` workers driving
    DISJOINT chains concurrently, sharded locks vs the HIVED_GLOBAL_LOCK
    single-lock escape hatch — same fleet, same load, interleaved in one
    process. Reports pods/sec for both, the speedup, and the
    lockWait/coreSchedule split of each run (doc/hot-path.md)."""
    import threading as _threading

    t0 = time.perf_counter()
    cfg_builder = lambda: build_concurrent_config(  # noqa: E731
        threads, hosts_per_family, block_ms
    )

    def run_once(force_global: bool) -> dict:
        sched = HivedScheduler(
            cfg_builder(),
            kube_client=NullKubeClient(),
            auto_admit=True,
            global_lock=force_global,
        )
        all_nodes = sched.core.configured_node_names()
        for n in all_nodes:
            sched.add_node(Node(name=n))
        family_nodes = {
            i: [n for n in all_nodes if n.startswith(f"cc{i}-")]
            for i in range(threads)
        }
        totals = [0] * threads
        barrier = _threading.Barrier(threads + 1)

        def worker(i: int) -> None:
            barrier.wait()
            totals[i] = _drive_family(
                sched, family_nodes[i], i, gangs_per_thread
            )

        ts = [
            _threading.Thread(target=worker, args=(i,)) for i in range(threads)
        ]
        for t in ts:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in ts:
            t.join()
        wall_s = time.perf_counter() - t0
        m = sched.get_metrics()
        return {
            "pods_scheduled": sum(totals),
            "wall_s": round(wall_s, 3),
            "pods_per_sec": round(sum(totals) / wall_s, 1) if wall_s else 0.0,
            "filter_count": m["filterCount"],
            "phases": {
                k: v
                for k, v in m["phases"].items()
                if k in ("lockWait", "coreSchedule")
            },
            "lockWaitByChain": m["lockWaitByChain"],
        }

    sharded = run_once(False)
    single = run_once(True)
    speedup = (
        round(sharded["pods_per_sec"] / single["pods_per_sec"], 2)
        if single["pods_per_sec"]
        else 0.0
    )
    return _stage_meta({
        "threads": threads,
        "gangs_per_thread": gangs_per_thread,
        "hosts_per_family": hosts_per_family,
        "fifo_block_ms": block_ms,
        "sharded": sharded,
        "global_lock": single,
        "speedup_vs_global_lock": speedup,
    }, threads * hosts_per_family, t0)


# ---------------------------------------------------------------------- #
# Boot stage (HIVED_BENCH_BOOT=1): the 50k-host boot ladder
# (doc/hot-path.md "Boot and transport plane")
# ---------------------------------------------------------------------- #

# First-boot wall budget at 50k synthetic hosts (compile + health-init +
# node-add + fingerprint; recovery replay excluded — it scales with BOUND
# PODS, not hosts). The ladder extrapolates linearly (every phase is
# O(fleet)) and the artifact records both the fit and, when
# HIVED_BENCH_BOOT_50K=1 (hack/soak.sh --boot-profile), the real rung.
BOOT_BUDGET_50K_S = 30.0


def _measure_boot(hosts: int, new_path: bool) -> dict:
    """One cold boot at ``hosts`` synthetic hosts through the production
    constructor + informer-shaped node replay. ``new_path=False`` pins
    every escape hatch to the pre-PR behavior (eager all-VC compile,
    per-leaf health bootstrap, per-node informer adds) — the A/B baseline
    measured on THIS host, not the ledger's recorded numbers."""
    from hivedscheduler_tpu.sim.fleet import fleet_dims_for_hosts

    env = {
        "HIVED_LAZY_VC": "1" if new_path else "0",
        "HIVED_BOOT_FOLD": "1" if new_path else "0",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        cfg = build_config(*fleet_dims_for_hosts(hosts))
        gc.collect()
        t0 = time.perf_counter()
        sched = HivedScheduler(cfg, kube_client=NullKubeClient())
        ctor_s = time.perf_counter() - t0
        nodes = [
            Node(name=n) for n in sched.core.configured_node_names()
        ]
        t1 = time.perf_counter()
        if new_path:
            sched.add_nodes(nodes)
        else:
            for n in nodes:
                sched.add_node(n)
        node_s = time.perf_counter() - t1
        sched.mark_ready()
        phases = {
            k: round(v, 4)
            for k, v in sched.core.boot_phase_seconds.items()
        }
        return {
            "hosts": hosts,
            "nodes": len(nodes),
            "constructor_s": round(ctor_s, 3),
            "node_add_s": round(node_s, 3),
            "total_s": round(ctor_s + node_s, 3),
            "phases": phases,
            "vcs_compiled": len(
                sched.core.vc_schedulers._compiled
            ),
        }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def bench_boot(
    ladder=(10368, 25920), reps: int = 3, include_50k: bool = False
) -> dict:
    """Boot ladder A/B (HIVED_BENCH_BOOT=1): cold-boot wall (compile +
    health-init + node-add + fingerprint) at 10k/25k synthetic hosts,
    new defaults vs the escape-hatched pre-PR path, interleaved, medians
    of ``reps`` at the first rung. The acceptance gate is >= 2.5x at the
    10k rung; the boot win is single-core (lazy VC compile + folded
    health bootstrap + batched adds + streamed fingerprint), so unlike
    bench_procs the gate does not presume spare cores — cpu_count is
    still stamped for honesty. The 50k rung runs only under
    HIVED_BENCH_BOOT_50K=1 (hack/soak.sh --boot-profile); otherwise the
    artifact extrapolates linearly from the ladder (every phase is
    O(fleet)) against BOOT_BUDGET_50K_S."""
    t0 = time.perf_counter()
    rungs: dict = {}
    for i, hosts in enumerate(ladder):
        n = reps if i == 0 else 1
        olds, news = [], []
        for _ in range(n):
            olds.append(_measure_boot(hosts, new_path=False))
            news.append(_measure_boot(hosts, new_path=True))
        old_s = statistics.median(r["total_s"] for r in olds)
        new_s = statistics.median(r["total_s"] for r in news)
        rungs[str(hosts)] = {
            "old_total_s": round(old_s, 3),
            "new_total_s": round(new_s, 3),
            "speedup": round(old_s / new_s, 2) if new_s else 0.0,
            "new_phases": news[-1]["phases"],
            "old_phases": olds[-1]["phases"],
            "vcs_compiled_new": news[-1]["vcs_compiled"],
        }
    top = str(ladder[-1])
    per_host = rungs[top]["new_total_s"] / float(top)
    extrapolated = round(per_host * 50_000, 2)
    out = {
        "ladder": rungs,
        "gate_rung_hosts": ladder[0],
        "speedup_10k": rungs[str(ladder[0])]["speedup"],
        "speedup_gate": 2.5,
        "gate_passed": rungs[str(ladder[0])]["speedup"] >= 2.5,
        "extrapolated_50k_s": extrapolated,
        "boot_budget_50k_s": BOOT_BUDGET_50K_S,
        "budget_met": extrapolated <= BOOT_BUDGET_50K_S,
    }
    if include_50k or os.environ.get("HIVED_BENCH_BOOT_50K") == "1":
        r50 = _measure_boot(50_000, new_path=True)
        out["measured_50k"] = r50
        out["budget_met"] = r50["total_s"] <= BOOT_BUDGET_50K_S
    return _stage_meta(out, max(ladder), t0)


# ---------------------------------------------------------------------- #
# Shard-ring A/B (HIVED_BENCH_RING=1): shared-memory filter payload ring
# vs pipe payloads at the 1728-host fleet (doc/hot-path.md "Boot and
# transport plane")
# ---------------------------------------------------------------------- #


def bench_ring_ab(
    families: int = 4,
    hosts_per_family: int = 432,
    n_shards: int = 2,
    reps: int = 5,
    calls: int = 120,
) -> dict:
    """filter_raw p50/p99 through the proc-shards frontend, shared-memory
    ring ON vs OFF (HIVED_SHARD_RING), same 1728-host fleet, identical
    pre-built JSON bodies, reps interleaved across the two live frontends
    and medians reported. Each rep schedules ``calls`` single-pod gangs
    measuring per-call wall, then drains them, so every rep sees the same
    state."""
    from hivedscheduler_tpu.scheduler.shards import ShardedScheduler

    t0 = time.perf_counter()
    modes = {}
    saved_ring = os.environ.get("HIVED_SHARD_RING")
    try:
        for label, ring in (("ring", "1"), ("pipe", "0")):
            os.environ["HIVED_SHARD_RING"] = ring
            cfg = build_concurrent_config(families, hosts_per_family)
            sched = ShardedScheduler(
                cfg, kube_client=NullKubeClient(), n_shards=n_shards,
                transport="proc", auto_admit=True,
            )
            nodes = sorted(
                f"cc{i}-s{s}-w{j}"
                for i in range(families)
                for s in range(max(1, hosts_per_family // 4))
                for j in range(4)
            )
            for n in nodes:
                sched.add_node(Node(name=n))
            modes[label] = (sched, nodes)

        lats: dict = {"ring": [], "pipe": []}
        for rep in range(reps):
            for label, (sched, nodes) in modes.items():
                bound = []
                per_call = []
                for i in range(calls):
                    fam = i % families
                    gname = f"{label}-r{rep}-g{i}"
                    group = {
                        "name": gname,
                        "members": [
                            {"podNumber": 1, "leafCellNumber": 4}
                        ],
                    }
                    pod = make_pod(
                        f"{gname}-0", f"{gname}-u0", f"vc{fam}", 0,
                        f"cc{fam}-chip", 4, group,
                    )
                    body = json.dumps(
                        ei.ExtenderArgs(
                            pod=pod, node_names=nodes
                        ).to_dict()
                    ).encode()
                    sched.add_pod(pod)
                    t1 = time.perf_counter()
                    r = json.loads(sched.filter_raw(body))
                    per_call.append(
                        (time.perf_counter() - t1) * 1e3
                    )
                    if r.get("NodeNames"):
                        bound.append(pod)
                sched.delete_pods(bound)
                lats[label].append(per_call)
        wire_meta = {
            label: _wire_meta(sched)
            for label, (sched, _nodes) in modes.items()
        }
    finally:
        if saved_ring is None:
            os.environ.pop("HIVED_SHARD_RING", None)
        else:
            os.environ["HIVED_SHARD_RING"] = saved_ring
        for sched, _ in modes.values():
            sched.close()

    def agg(all_reps):
        flat = [x for rep in all_reps for x in rep]
        p50, p99 = _percentiles(flat)
        return round(p50, 3), round(p99, 3)

    ring_p50, ring_p99 = agg(lats["ring"])
    pipe_p50, pipe_p99 = agg(lats["pipe"])
    return _stage_meta({
        "families": families,
        "hosts_per_family": hosts_per_family,
        "n_shards": n_shards,
        "reps": reps,
        "calls_per_rep": calls,
        "ring_p50_ms": ring_p50,
        "ring_p99_ms": ring_p99,
        "pipe_p50_ms": pipe_p50,
        "pipe_p99_ms": pipe_p99,
        "p50_improvement_pct": round(
            (1.0 - ring_p50 / pipe_p50) * 100.0, 1
        ) if pipe_p50 else 0.0,
        # Codec split + bytes-per-frame histogram (ISSUE 16 satellite):
        # the transport win is auditable in the artifact, not just the
        # throughput delta.
        "wire": wire_meta,
    }, families * hosts_per_family, t0)


# ---------------------------------------------------------------------- #
# One-wire A/B (HIVED_BENCH_WIRE=1): binary pipe/ring frames + delta
# suggested sets vs the legacy pickle path (doc/hot-path.md "One wire")
# ---------------------------------------------------------------------- #


def _wire_meta(sched) -> dict:
    """Codec split + per-codec power-of-two frame-size histogram from one
    scheduler's metrics snapshot (zeros for the in-process core, which
    has no internal transport)."""
    m = sched.get_metrics()
    return {
        "bytes_by_codec": dict(m.get("wireBytesTotal") or {}),
        "frame_hist": (
            (m.get("shardWire") or {}).get("frameHistogram") or {}
        ),
        "delta_resyncs": int(m.get("deltaSuggestedResyncCount", 0) or 0),
    }


def _pipe_codec_bytes(sched) -> dict:
    """Per-codec TRANSPORT bytes only (pipe + ring frames across all
    backends), excluding the frontend HTTP envelope — the bytes-on-wire
    number the churn gate measures."""
    total = {"binary": 0, "pickle": 0}
    for b in getattr(sched, "shards", ()):
        lock = getattr(b, "_stats_lock", None)
        if lock is None:
            continue
        with lock:
            for codec, n in b.wire_bytes.items():
                total[codec] = total.get(codec, 0) + n
    return total


def bench_wire_ab(
    families: int = 4,
    hosts_per_family: int = 432,
    n_shards: int = 2,
    reps: int = 5,
    calls: int = 120,
    churn_calls: int = 40,
) -> dict:
    """One-wire A/B (ISSUE 16): binary frames (``HIVED_WIRE=1``) vs the
    legacy pickle path (``HIVED_WIRE=0``) through the SAME proc-shards
    ``filter_raw`` entry at the 1728-host fleet, identical pre-built JSON
    bodies, reps interleaved across the two live frontends. Two regimes
    per rep:

    - **steady**: one fixed suggested list every call — after the first
      call the PR-12 token replaces the list in BOTH modes, so the frames
      are pod-dict-sized and the A/B isolates the per-frame codec;
    - **churn**: the node list changes by one host per call — the legacy
      path re-sends the full O(fleet) list every call, the binary path
      ships a delta edit script against the shard's last acked set. The
      per-codec transport-byte counters give bytes-on-wire for each.

    Gates are RECORDED, not asserted (the test asserts wiring, the doc
    adjudicates): steady-state p50 ratio against the 1.3x acceptance
    gate, churn bytes ratio against the 10x delta gate."""
    from hivedscheduler_tpu.scheduler.shards import ShardedScheduler

    t0 = time.perf_counter()
    modes: dict = {}
    saved_wire = os.environ.get("HIVED_WIRE")
    try:
        for label, wire_env in (("binary", "1"), ("legacy", "0")):
            os.environ["HIVED_WIRE"] = wire_env
            cfg = build_concurrent_config(families, hosts_per_family)
            sched = ShardedScheduler(
                cfg, kube_client=NullKubeClient(), n_shards=n_shards,
                transport="proc", auto_admit=True,
            )
            nodes = sorted(
                f"cc{i}-s{s}-w{j}"
                for i in range(families)
                for s in range(max(1, hosts_per_family // 4))
                for j in range(4)
            )
            for n in nodes:
                sched.add_node(Node(name=n))
            modes[label] = (sched, nodes)

        def one_call(sched, nodes, pod):
            body = json.dumps(
                ei.ExtenderArgs(pod=pod, node_names=nodes).to_dict()
            ).encode()
            sched.add_pod(pod)
            t1 = time.perf_counter()
            r = json.loads(sched.filter_raw(body))
            ms = (time.perf_counter() - t1) * 1e3
            return ms, (pod if r.get("NodeNames") else None)

        steady: dict = {"binary": [], "legacy": []}
        churn: dict = {"binary": [], "legacy": []}
        churn_bytes = {"binary": 0, "legacy": 0}
        for rep in range(reps):
            for label, (sched, nodes) in modes.items():
                bound = []
                for i in range(calls):
                    fam = i % families
                    gname = f"{label}-r{rep}-g{i}"
                    group = {
                        "name": gname,
                        "members": [
                            {"podNumber": 1, "leafCellNumber": 4}
                        ],
                    }
                    pod = make_pod(
                        f"{gname}-0", f"{gname}-u0", f"vc{fam}", 0,
                        f"cc{fam}-chip", 4, group,
                    )
                    ms, b = one_call(sched, nodes, pod)
                    steady[label].append(ms)
                    if b is not None:
                        bound.append(b)
                before = _pipe_codec_bytes(sched)
                for i in range(churn_calls):
                    # One-host churn per call: the suggested list loses a
                    # rotating host (and regains the previous one) — a
                    # 2-op delta for the binary path, a full O(fleet)
                    # re-send for the legacy path. The rotation index
                    # advances ACROSS reps so every churned set is new
                    # to the frontend (a repeated set would ride the
                    # PR-12 token in both modes and measure nothing).
                    k = (rep * churn_calls + i) % len(nodes)
                    churned = nodes[:k] + nodes[k + 1:]
                    fam = i % families
                    gname = f"{label}-r{rep}-c{i}"
                    group = {
                        "name": gname,
                        "members": [
                            {"podNumber": 1, "leafCellNumber": 4}
                        ],
                    }
                    pod = make_pod(
                        f"{gname}-0", f"{gname}-u0", f"vc{fam}", 0,
                        f"cc{fam}-chip", 4, group,
                    )
                    ms, b = one_call(sched, churned, pod)
                    churn[label].append(ms)
                    if b is not None:
                        bound.append(b)
                after = _pipe_codec_bytes(sched)
                churn_bytes[label] += sum(after.values()) - sum(
                    before.values()
                )
                sched.delete_pods(bound)
        wire_meta = {
            label: _wire_meta(sched)
            for label, (sched, _nodes) in modes.items()
        }
    finally:
        if saved_wire is None:
            os.environ.pop("HIVED_WIRE", None)
        else:
            os.environ["HIVED_WIRE"] = saved_wire
        for sched, _ in modes.values():
            sched.close()

    s_bin, s_bin99 = _percentiles(steady["binary"])
    s_leg, s_leg99 = _percentiles(steady["legacy"])
    c_bin, _ = _percentiles(churn["binary"])
    c_leg, _ = _percentiles(churn["legacy"])
    bytes_ratio = (
        churn_bytes["legacy"] / churn_bytes["binary"]
        if churn_bytes["binary"] else 0.0
    )
    return _stage_meta({
        "families": families,
        "hosts_per_family": hosts_per_family,
        "n_shards": n_shards,
        "reps": reps,
        "calls_per_rep": calls,
        "churn_calls_per_rep": churn_calls,
        "steady_binary_p50_ms": round(s_bin, 3),
        "steady_binary_p99_ms": round(s_bin99, 3),
        "steady_legacy_p50_ms": round(s_leg, 3),
        "steady_legacy_p99_ms": round(s_leg99, 3),
        "steady_p50_ratio": round(s_leg / s_bin, 3) if s_bin else 0.0,
        "churn_binary_p50_ms": round(c_bin, 3),
        "churn_legacy_p50_ms": round(c_leg, 3),
        "churn_bytes_binary": churn_bytes["binary"],
        "churn_bytes_legacy": churn_bytes["legacy"],
        "churn_bytes_ratio": round(bytes_ratio, 1),
        "gates": {
            "steady_p50_ratio_min": 1.3,
            "steady_gate_met": bool(
                s_bin and s_leg / s_bin >= 1.3
            ),
            "churn_bytes_ratio_min": 10.0,
            "churn_gate_met": bool(bytes_ratio >= 10.0),
        },
        "wire": wire_meta,
    }, families * hosts_per_family, t0)


# ---------------------------------------------------------------------- #
# Multi-process core stage (HIVED_BENCH_PROCS=1): per-chain-family worker
# shards vs the in-process core (doc/hot-path.md "The multi-process
# contract")
# ---------------------------------------------------------------------- #


def _family_fill_load(fam: int, rep: str, nodes, n_gangs: int):
    """Pre-built (pods, JSON bodies) for one family's fill phase: 2-pod
    4-chip gangs until the family is full. Bodies are built OUTSIDE the
    measured window — the webserver receives bodies off the wire; building
    them is the client's work, not the scheduler's."""
    load = []
    for g in range(n_gangs):
        gname = f"cc{fam}-{rep}-g{g}"
        group = {
            "name": gname,
            "members": [{"podNumber": 2, "leafCellNumber": 4}],
        }
        pods = [
            make_pod(
                f"{gname}-{k}", f"{gname}-u{k}", f"vc{fam}", 0,
                f"cc{fam}-chip", 4, group,
            )
            for k in range(2)
        ]
        bodies = [
            json.dumps(
                ei.ExtenderArgs(pod=p, node_names=nodes).to_dict()
            ).encode()
            for p in pods
        ]
        load.append((pods, bodies))
    return load


def _measure_fill(filter_json, lanes) -> tuple:
    """Run every lane's fill concurrently; returns (pods bound, wall s).
    Two feeder lanes per family keep a pipelined shard fed back-to-back."""
    import threading as _threading

    totals = [0] * len(lanes)
    bound: list = [[] for _ in lanes]
    barrier = _threading.Barrier(len(lanes) + 1)

    def worker(li: int) -> None:
        barrier.wait()
        for pods, bodies in lanes[li]:
            for p, body in zip(pods, bodies):
                r = json.loads(filter_json(body))
                if r.get("NodeNames"):
                    totals[li] += 1
                    bound[li].append(p)

    threads = [
        _threading.Thread(target=worker, args=(li,))
        for li in range(len(lanes))
    ]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return sum(totals), wall, [p for lane in bound for p in lane]


def _procs_mode(n_shards: int, families: int, hosts_per_family: int):
    """Build one measurement subject:
    (filter_json, drain, close, fam_nodes, sched).
    n_shards == 0 is the in-process core driven through the exact JSON
    decode/encode work its webserver does per request — the
    HIVED_PROC_SHARDS=0 baseline."""
    from hivedscheduler_tpu.scheduler.shards import ShardedScheduler

    cfg = build_concurrent_config(families, hosts_per_family)
    if n_shards > 0:
        sched = ShardedScheduler(
            cfg, kube_client=NullKubeClient(), n_shards=n_shards,
            transport="proc", auto_admit=True,
        )
        filter_json = sched.filter_raw
        drain = sched.delete_pods
        close = sched.close
    else:
        sched = HivedScheduler(
            cfg, kube_client=NullKubeClient(), auto_admit=True
        )

        def filter_json(body: bytes) -> bytes:
            args = ei.ExtenderArgs.from_dict(json.loads(body))
            return json.dumps(
                sched.filter_routine(args).to_dict()
            ).encode()

        def drain(pods) -> None:
            for p in pods:
                sched.delete_pod(p)

        def close() -> None:
            pass

    all_nodes = sorted(
        f"cc{i}-s{s}-w{j}"
        for i in range(families)
        for s in range(max(1, hosts_per_family // 4))
        for j in range(4)
    )
    for n in all_nodes:
        sched.add_node(Node(name=n))
    fam_nodes = {
        i: [n for n in all_nodes if n.startswith(f"cc{i}-")]
        for i in range(families)
    }
    return filter_json, drain, close, fam_nodes, sched


def bench_procs(
    shard_counts=(1, 2, 4),
    families: int = 4,
    hosts_per_family: int = 108,
    reps: int = 5,
    feeders_per_family: int = 2,
) -> dict:
    """Aggregate fill-phase filter throughput (pods/s) over disjoint
    chain families: N worker PROCESSES vs the in-process sharded core
    (``HIVED_PROC_SHARDS=0``), same 432-host fleet, same JSON-bytes
    request path, same concurrent client lanes. Reps are INTERLEAVED
    across modes (this host's background noise swings run-to-run far
    more than rep-to-rep) and the medians reported.

    The GIL ceiling is the story: in-process, N client lanes share one
    interpreter, so filter COMPUTE serializes no matter how many chains
    PR 5's lock sharding lets proceed concurrently; worker processes
    compute in true parallel, bounded by cores. The speedup gate is
    therefore core-scaled: the 2.5x acceptance number presumes >= 5
    usable cores (4 workers + routing parent); below that the stage
    reports the curve and the achievable ceiling (``cpu_count``) so the
    artifact is honest about the host it ran on."""
    t0 = time.perf_counter()
    modes = {0: _procs_mode(0, families, hosts_per_family)}
    for n in shard_counts:
        modes[n] = _procs_mode(n, families, hosts_per_family)
    rates: dict = {n: [] for n in modes}
    wire_meta: dict = {}
    try:
        for rep in range(reps):
            for n, (filter_json, drain, _close, fam_nodes, _s) in (
                modes.items()
            ):
                lanes = []
                for fam in range(families):
                    load = _family_fill_load(
                        fam, f"m{n}r{rep}", fam_nodes[fam],
                        max(1, hosts_per_family // 2),
                    )
                    for li in range(feeders_per_family):
                        lanes.append(load[li::feeders_per_family])
                pods, wall, bound = _measure_fill(filter_json, lanes)
                rates[n].append(pods / wall if wall else 0.0)
                drain(bound)
        wire_meta = {
            str(n): _wire_meta(mode[4]) for n, mode in modes.items()
        }
    finally:
        for _f, _d, close, _n, _s in modes.values():
            close()
    medians = {
        n: round(statistics.median(r), 1) for n, r in rates.items()
    }
    base = medians[0] or 1.0
    curve = {
        str(n): {
            "pods_per_sec": medians[n],
            "speedup_vs_inproc": round(medians[n] / base, 2),
        }
        for n in sorted(modes)
    }
    best = max(
        (n for n in modes if n > 0),
        key=lambda n: medians[n],
    )
    return _stage_meta({
        "families": families,
        "hosts_per_family": hosts_per_family,
        "reps": reps,
        "feeders_per_family": feeders_per_family,
        "inproc_pods_per_sec": medians[0],
        "curve": curve,
        "best_shard_count": best,
        "best_speedup_vs_inproc": curve[str(best)]["speedup_vs_inproc"],
        # Per-mode codec split + frame-size histogram (ISSUE 16
        # satellite; zeros for the in-process "0" mode).
        "wire": wire_meta,
    }, families * hosts_per_family, t0)


def bench_fleet_sweep(
    sizes=(108, 216, 432),
    families: int = 4,
    procs: int = 4,
    reps: int = 3,
) -> dict:
    """Fleet-size sweep (432 -> 864 -> 1728 hosts at 4 families): the
    in-process core's fill throughput as the fleet grows — the
    single-process SATURATION point (where adding hosts stops adding
    pods/s because one interpreter is compute-bound) — against the
    ``procs``-shard frontend at the same sizes. The saturation point is
    the instrument ROADMAP item 1 asked for: the fleet size beyond which
    only parallel compute (more shards) raises throughput."""
    t0 = time.perf_counter()
    out: dict = {"families": families, "procs": procs, "sizes": {}}
    prev_rate = None
    saturation = None
    for hosts_per_family in sizes:
        modes = {
            0: _procs_mode(0, families, hosts_per_family),
            procs: _procs_mode(procs, families, hosts_per_family),
        }
        rates: dict = {n: [] for n in modes}
        try:
            for rep in range(reps):
                for n, (fj, drain, _c, fam_nodes, _s) in modes.items():
                    lanes = []
                    for fam in range(families):
                        load = _family_fill_load(
                            fam, f"s{hosts_per_family}m{n}r{rep}",
                            fam_nodes[fam],
                            max(1, hosts_per_family // 2),
                        )
                        lanes.append(load[0::2])
                        lanes.append(load[1::2])
                    pods, wall, bound = _measure_fill(fj, lanes)
                    rates[n].append(pods / wall if wall else 0.0)
                    drain(bound)
        finally:
            for _f, _d, close, _n, _s in modes.values():
                close()
        inproc = round(statistics.median(rates[0]), 1)
        sharded = round(statistics.median(rates[procs]), 1)
        total_hosts = families * hosts_per_family
        out["sizes"][str(total_hosts)] = {
            "inproc_pods_per_sec": inproc,
            "procs_pods_per_sec": sharded,
            "procs_speedup": round(inproc and sharded / inproc, 2),
        }
        if (
            saturation is None
            and prev_rate is not None
            and inproc <= prev_rate * 1.10
        ):
            # Adding hosts stopped buying >10% throughput: the single
            # process is compute-bound, not capacity-bound.
            saturation = total_hosts
        prev_rate = max(prev_rate or 0.0, inproc)
    out["single_process_saturation_hosts"] = saturation
    return _stage_meta(out, families * max(sizes), t0)


def bench_supervise(
    n_shards: int = 4,
    families: int = 4,
    hosts_per_family: int = 108,
    warm_calls: int = 24,
    steady_calls: int = 160,
    degraded_calls: int = 160,
    bind_gangs_per_family: int = 6,
) -> dict:
    """Shard supervision plane acceptance stage (HIVED_BENCH_SUPERVISE=1;
    doc/fault-model.md "Shard supervision plane") at the 432-host proc
    fleet: SIGKILL one REAL worker process mid-load and measure the
    blast radius.

    Three properties, two asserted here unconditionally:

    1. **Isolation** (core-scaled, like bench_procs) — surviving shards'
       filter p99 while the victim is down stays within 3% of their
       steady-state p99: detection and degraded answers must not
       serialize healthy traffic. The gate presumes every worker plus
       the routing parent gets a core; the __main__ driver asserts it
       only on >= 5 usable cores, the stage always reports the delta.
    2. **Degraded admission** (asserted) — every request routed to the
       down shard is answered WAIT with the ``shardDown`` certificate
       (failed-node attribution, epoch-stamped) and never raises.
    3. **Zero placements lost or duplicated** (asserted) — every bind
       confirmed before the kill resolves to the SAME node after hot
       resurrection, the victim shard's pod ledger is unchanged, and
       fresh work schedules again (capacity neither leaked nor
       double-booked)."""
    import signal as _signal

    from hivedscheduler_tpu.scheduler.decisions import GATE_SHARD_DOWN
    from hivedscheduler_tpu.scheduler.shards import ShardedScheduler

    t0 = time.perf_counter()
    front = ShardedScheduler(
        build_concurrent_config(families, hosts_per_family),
        kube_client=NullKubeClient(), n_shards=n_shards,
        transport="proc", auto_admit=True,
    )
    front.supervisor.backoff_base_s = 0.0
    try:
        for n in front.configured_node_names():
            front.add_node(Node(name=n))
        fam_nodes = {
            fam: [
                n for n in front.configured_node_names()
                if n.startswith(f"cc{fam}-")
            ]
            for fam in range(families)
        }
        victim = 0
        victim_chains = set(front.shards[victim].owned_chains)
        down_fams = [
            fam for fam in range(families)
            if any(
                c in victim_chains
                for c in front.routing.leaf_chains.get(
                    f"cc{fam}-chip", ()
                )
            )
        ]
        live_fams = [f for f in range(families) if f not in down_fams]
        assert down_fams and live_fams, (down_fams, live_fams)

        def _pod(fam: int, tag: str, chips: int):
            gname = f"sup-{tag}"
            return make_pod(
                gname, f"{gname}-u", f"vc{fam}", 0, f"cc{fam}-chip",
                chips,
                {
                    "name": gname,
                    "members": [
                        {"podNumber": 1, "leafCellNumber": chips}
                    ],
                },
            )

        # Confirmed binds: the lost/duplicated substrate. The informer
        # confirm in miniature — add_pod -> filter -> update_pod(bound)
        # — so the supervisor mirror carries every placement.
        placements: dict = {}
        for fam in range(families):
            for g in range(bind_gangs_per_family):
                pod = _pod(fam, f"bind-f{fam}-g{g}", 4)
                front.add_pod(pod)
                r = front.filter_routine(
                    ei.ExtenderArgs(pod=pod, node_names=fam_nodes[fam])
                )
                assert r.node_names, (fam, g, r.failed_nodes)
                bp, _state = front.get_status_pod(pod.uid)
                confirmed = Pod(
                    name=bp.name, namespace=bp.namespace, uid=bp.uid,
                    annotations=dict(bp.annotations),
                    node_name=bp.node_name, phase="Running",
                    resource_limits=dict(bp.resource_limits),
                )
                front.update_pod(pod, confirmed)
                placements[pod.uid] = bp.node_name
        victim_ledger = front.shards[victim].call("list_state")

        def probe_ms(fam: int, tag: str):
            pod = _pod(fam, tag, 1)
            args = ei.ExtenderArgs(
                pod=pod, node_names=fam_nodes[fam]
            )
            t1 = time.perf_counter()
            r = front.filter_routine(args)
            dt = (time.perf_counter() - t1) * 1000.0
            if r.node_names:
                front.delete_pod(pod)
            return dt, r, pod

        for i in range(warm_calls):
            probe_ms(live_fams[i % len(live_fams)], f"warm-{i}")
        steady: list = []
        for i in range(steady_calls):
            dt, _r, _p = probe_ms(
                live_fams[i % len(live_fams)], f"steady-{i}"
            )
            steady.append(dt)

        # Mid-load kill: a REAL SIGKILL on the worker process, then the
        # degraded window interleaves surviving-shard latency probes
        # with requests routed at the corpse.
        proc = front.shards[victim]._proc
        os.kill(proc.pid, _signal.SIGKILL)
        proc.join(timeout=10.0)

        degraded: list = []
        degraded_waits = 0
        first_cert = None
        for i in range(degraded_calls):
            dt, _r, _p = probe_ms(
                live_fams[i % len(live_fams)], f"deg-{i}"
            )
            degraded.append(dt)
            fam = down_fams[i % len(down_fams)]
            pod = _pod(fam, f"down-{i}", 1)
            # Must not raise: degraded admission is WAIT, never a 500.
            rr = front.filter_routine(
                ei.ExtenderArgs(pod=pod, node_names=fam_nodes[fam])
            )
            assert not rr.node_names, (i, rr.node_names)
            assert set(rr.failed_nodes or {}) == {
                constants.COMPONENT_NAME
            }, rr.failed_nodes
            degraded_waits += 1
            if first_cert is None:
                rec = front.decisions.lookup(pod.uid)
                assert rec and rec.get("verdict") == "wait", rec
                cert = rec.get("certificate") or {}
                assert cert.get("gate") == GATE_SHARD_DOWN, rec
                vector = cert.get("vector") or {}
                assert vector.get("shard") == victim, rec
                assert "shardEpoch" in vector, rec
                first_cert = cert

        res = front.supervisor.check_now()
        assert victim in res["resurrected"], res
        sup = front.supervisor.snapshot()[victim]
        assert sup["status"] == "up" and sup["restarts"] >= 1, sup

        # Zero lost: every confirmed bind resolves to the same node.
        post = {}
        for uid in placements:
            found = front.get_status_pod(uid)
            post[uid] = found[0].node_name if found else None
        moved = {
            u: (placements[u], post[u])
            for u in placements if post[u] != placements[u]
        }
        assert not moved, moved
        # Zero duplicated: the resurrected ledger matches the pre-kill
        # ledger exactly, and fresh work still schedules (capacity
        # neither leaked nor double-booked).
        assert front.shards[victim].call("list_state") == (
            victim_ledger
        )
        _dt, r_post, p_post = probe_ms(down_fams[0], "post-resurrect")
        assert r_post.node_names, r_post.failed_nodes
    finally:
        front.close()

    steady_p50, steady_p99 = _percentiles(steady)
    degraded_p50, degraded_p99 = _percentiles(degraded)
    delta_pct = (
        (degraded_p99 / steady_p99 - 1.0) * 100.0 if steady_p99 else 0.0
    )
    return _stage_meta({
        "n_shards": n_shards,
        "families": families,
        "hosts_per_family": hosts_per_family,
        "steady_calls": steady_calls,
        "degraded_calls": degraded_calls,
        "confirmed_binds": len(placements),
        "steady_p50_ms": round(steady_p50, 3),
        "steady_p99_ms": round(steady_p99, 3),
        "degraded_p50_ms": round(degraded_p50, 3),
        "degraded_p99_ms": round(degraded_p99, 3),
        "surviving_p99_delta_pct": round(delta_pct, 2),
        "p99_budget_pct": 3.0,
        "within_budget": delta_pct <= 3.0,
        "degraded_waits": degraded_waits,
        "degraded_cert": first_cert,
        "restarts": sup["restarts"],
        "placements_lost": 0,      # asserted above
        "placements_duplicated": 0,  # asserted above
    }, families * hosts_per_family, t0)


def bench_outage(
    cubes: int = 16,
    slices: int = 40,
    solos: int = 16,
    n_gangs: int = 240,
    warm_calls: int = 24,
    steady_calls: int = 160,
    degraded_calls: int = 160,
    journal_writes: int = 64,
    parked_binds: int = 8,
) -> dict:
    """Control-plane weather plane acceptance stage (HIVED_BENCH_OUTAGE=1;
    doc/fault-model.md "Control-plane weather plane") at the 432-host
    fleet: a full apiserver BLACKOUT struck mid-load, measured end to end.

    Four properties, three asserted unconditionally:

    1. **Zero 500s** (asserted) — under blackout every filter answers
       WAIT with the weather-epoch certificate and every bind refuses
       with a retriable 503 ``apiserverOutage``; nothing raises anything
       else.
    2. **Degraded latency** (reported; the >= 3-core driver gate asserts)
       — filter p99 through the blackout window (first-seen outage WAITs
       plus the fast-path retry storm) stays within 3% of the clear-sky
       steady p99: answering an outage must not cost more than serving.
    3. **Write-behind accounting** (asserted) — every durable write
       issued under blackout journals latest-wins and SWALLOWS (the
       caller's watermarks advance as under clear skies), nothing reaches
       the apiserver during the window, and after the heal
       ``drained + superseded == journaled`` with zero drops and an empty
       journal.
    4. **Convergence** (asserted) — the post-drain apiserver holds the
       final ledger blob, the folded annotation patch, and the eviction;
       the parked binds land; fresh work schedules again. The drain wall
       time is the stage's measured blackout-recovery cost."""
    import random as _random

    from hivedscheduler_tpu.api.types import WebServerError
    from hivedscheduler_tpu.scheduler import weather as weather_mod
    from hivedscheduler_tpu.scheduler.kube import (
        KubeAPIError,
        RetryingKubeClient,
    )

    class _OutageKubeClient(NullKubeClient):
        """NullKubeClient + an outage switch: while set, EVERY verb —
        reads and writes alike — fails 503 retryably (total apiserver
        unreachability). Durable effects are recorded so the post-drain
        convergence can be asserted."""

        def __init__(self) -> None:
            super().__init__()
            self.outage = False
            self.state = None
            self.snapshot_chunks = None
            self.patches: list = []
            self.evicted: list = []

        def _check(self, method: str, path: str) -> None:
            if self.outage:
                raise KubeAPIError(
                    method, path, 503,
                    "apiserver unreachable (outage window)",
                )

        def bind_pod(self, binding_pod: Pod) -> None:
            self._check("POST", "/binding")
            super().bind_pod(binding_pod)

        def persist_scheduler_state(self, payload: str) -> None:
            self._check("PUT", "/configmaps/state")
            self.state = payload

        def persist_snapshot(self, chunks) -> None:
            self._check("PUT", "/configmaps/snapshot")
            self.snapshot_chunks = list(chunks)

        def patch_pod_annotations(self, pod, annotations) -> None:
            self._check("PATCH", "/pods")
            self.patches.append((pod.uid, dict(annotations)))

        def evict_pod(self, pod: Pod) -> None:
            self._check("DELETE", "/pods")
            self.evicted.append(pod.uid)

        def read_lease(self):
            self._check("GET", "/leases")
            return None

    t0 = time.perf_counter()
    inner = _OutageKubeClient()
    sched = HivedScheduler(
        build_config(cubes=cubes, slices=slices, solos=solos),
        kube_client=inner,
        force_bind_executor=lambda fn: fn(),
    )
    sched.kube_client = RetryingKubeClient(
        inner, scheduler=sched, max_attempts=4,
        backoff_initial_s=0.001, backoff_max_s=0.002,
        sleep=lambda s: None, jitter_rng=_random.Random(11),
    )
    nodes = sched.core.configured_node_names()
    for n in nodes:
        sched.add_node(Node(name=n))
    sched.mark_ready()
    _drive_and_confirm(sched, nodes, n_gangs)
    vane, journal = sched.weather_vane, sched.intent_journal

    probe_i = [0]

    def probe_ms(tag: str):
        probe_i[0] += 1
        gname = f"wx-{tag}-{probe_i[0]}"
        pod = make_pod(
            gname, f"{gname}-u", "research", 0, "v5e-chip", 1,
            {"name": gname,
             "members": [{"podNumber": 1, "leafCellNumber": 1}]},
        )
        sched.add_pod(pod)
        t1 = time.perf_counter()
        r = sched.filter_routine(
            ei.ExtenderArgs(pod=pod, node_names=nodes)
        )
        dt = (time.perf_counter() - t1) * 1000.0
        return dt, r, pod

    for i in range(warm_calls):
        _dt, _r, p = probe_ms("warm")
        sched.delete_pod(p)
    steady: list = []
    for i in range(steady_calls):
        dt, _r, p = probe_ms("steady")
        steady.append(dt)
        sched.delete_pod(p)

    # Park bind writes: filtered (assume-bound) before the storm, bound
    # during and after it — the retriable-refusal substrate.
    parked = []
    for i in range(parked_binds):
        _dt, r, pod = probe_ms("park")
        assert r.node_names, (i, r.failed_nodes)
        parked.append((pod, r.node_names[0]))

    # ---- the blackout strikes ---- #
    inner.outage = True
    guard = 0
    while vane.state() != weather_mod.BLACKOUT:
        sched.kube_client.weather_probe()
        guard += 1
        assert guard <= vane.blackout_after, vane.snapshot()
    epoch_black = vane.epoch

    http_500s = 0
    bind_refusals = 0
    for pod, node in parked:
        # Must refuse retriably — 503 with the apiserverOutage marker,
        # never a 500 or an unhandled exception.
        try:
            sched.bind_routine(ei.ExtenderBindingArgs(
                pod_name=pod.name, pod_namespace=pod.namespace,
                pod_uid=pod.uid, node=node,
            ))
            http_500s += 1  # a silent success under blackout is a bug
        except WebServerError as e:
            if e.code == 503 and "apiserverOutage" in e.message:
                bind_refusals += 1
            else:
                http_500s += 1
        except Exception:  # noqa: BLE001
            http_500s += 1

    # Durable writes under blackout: journal-and-swallow, latest-wins.
    patch_pod = Pod(name="wx-patch", uid="wx-patch-u")
    evict_pod_obj = Pod(name="wx-evict", uid="wx-evict-u")
    pre_state = inner.state
    pre_patches = len(inner.patches)
    for i in range(journal_writes):
        kind = i % 4
        if kind == 0:
            sched.kube_client.persist_scheduler_state(f"ledger-{i}")
        elif kind == 1:
            sched.kube_client.persist_snapshot([f"meta-{i}", f"c-{i}"])
        elif kind == 2:
            sched.kube_client.patch_pod_annotations(
                patch_pod, {"wx": f"v{i}", f"k{i % 3}": f"v{i}"}
            )
        else:
            sched.kube_client.evict_pod(evict_pod_obj)
    assert inner.state == pre_state and len(inner.patches) == pre_patches, (
        "durable writes leaked through the outage window"
    )
    assert journal.depth() == 4, journal.counters()  # latest-wins per key

    # Degraded serving: first-seen pods get the epoch-stamped outage
    # WAIT; their retry storm is answered from the negative cache.
    degraded: list = []
    outage_waits = 0
    fast0 = sched.get_metrics()["fastWaitCount"]
    degraded_pods = []
    for i in range(degraded_calls):
        try:
            if i % 2 == 0 or not degraded_pods:
                dt, r, p = probe_ms("deg")
                degraded_pods.append(p)
            else:
                p = degraded_pods[-1]
                t1 = time.perf_counter()
                r = sched.filter_routine(
                    ei.ExtenderArgs(pod=p, node_names=nodes)
                )
                dt = (time.perf_counter() - t1) * 1000.0
        except Exception:  # noqa: BLE001
            http_500s += 1
            continue
        degraded.append(dt)
        assert not r.node_names, (i, r.node_names)
        assert set(r.failed_nodes or {}) == {constants.COMPONENT_NAME}
        reason = r.failed_nodes[constants.COMPONENT_NAME]
        assert f"weather epoch {epoch_black}" in reason, reason
        outage_waits += 1
    fast_waits = sched.get_metrics()["fastWaitCount"] - fast0
    assert http_500s == 0, http_500s

    # ---- the weather heals: measured drain ---- #
    inner.outage = False
    guard = 0
    while not vane.drain_ok():
        sched.kube_client.weather_probe()
        guard += 1
        assert guard <= vane.clear_after + 1, vane.snapshot()
    t_drain = time.perf_counter()
    drained = sched.kube_client.maybe_drain()
    drain_ms = (time.perf_counter() - t_drain) * 1000.0
    jc = journal.counters()
    assert jc["depth"] == 0 and jc["dropped"] == 0, jc
    assert jc["drained"] + jc["superseded"] == jc["journaled"], jc
    # Convergence: the final intents reached the apiserver.
    assert inner.state is not None and inner.state.startswith("ledger-")
    assert inner.patches and inner.patches[-1][0] == patch_pod.uid
    folded = inner.patches[-1][1]
    assert folded.get("wx", "").startswith("v"), folded
    assert inner.snapshot_chunks is not None
    assert evict_pod_obj.uid in inner.evicted

    # Clear the sky fully (the write class recovers off the drain) and
    # prove the parked binds + fresh work land.
    guard = 0
    while vane.state() != weather_mod.CLEAR:
        sched.kube_client.weather_probe()
        sched.kube_client.persist_scheduler_state("wx-clear")
        guard += 1
        assert guard <= vane.blackout_after, vane.snapshot()
    bound0 = len(inner.bound_pods)
    for pod, node in parked:
        sched.bind_routine(ei.ExtenderBindingArgs(
            pod_name=pod.name, pod_namespace=pod.namespace,
            pod_uid=pod.uid, node=node,
        ))
    assert len(inner.bound_pods) - bound0 == len(parked)
    _dt, r_post, p_post = probe_ms("post")
    assert r_post.node_names, r_post.failed_nodes

    steady_p50, steady_p99 = _percentiles(steady)
    degraded_p50, degraded_p99 = _percentiles(degraded)
    delta_pct = (
        (degraded_p99 / steady_p99 - 1.0) * 100.0 if steady_p99 else 0.0
    )
    m = sched.get_metrics()
    return _stage_meta({
        "n_gangs": n_gangs,
        "steady_calls": steady_calls,
        "degraded_calls": degraded_calls,
        "journal_writes": journal_writes,
        "steady_p50_ms": round(steady_p50, 3),
        "steady_p99_ms": round(steady_p99, 3),
        "degraded_p50_ms": round(degraded_p50, 3),
        "degraded_p99_ms": round(degraded_p99, 3),
        "degraded_p99_delta_pct": round(delta_pct, 2),
        "p99_budget_pct": 3.0,
        "within_budget": delta_pct <= 3.0,
        "http_500s": 0,              # asserted above
        "bind_refusals_503": bind_refusals,
        "outage_waits": outage_waits,
        "fast_waits": fast_waits,
        "blackout_epoch": epoch_black,
        "drained": drained,
        "drain_ms": round(drain_ms, 3),
        "journal": jc,
        "weather": vane.snapshot(),
        "outage_wait_metric": m["outageWaitCount"],
        "outage_bind_refused_metric": m["outageBindRefusedCount"],
    }, 16 * cubes + 4 * slices + solos, t0)


# ---------------------------------------------------------------------- #
# Warehouse-scale hot-path stages (ISSUE 9): per-priority view slots A/B,
# relist fast-path A/B, and the trace-driven fleet-size trend
# (doc/hot-path.md "Warehouse-scale profile")
# ---------------------------------------------------------------------- #


def bench_view_slots_ab(
    cubes: int = 64,
    slices: int = 160,
    solos: int = 64,
    arrivals: int = 150,
    reps: int = 3,
) -> dict:
    """Per-priority cached view slots A/B at the 1728-host fleet: the
    mixed-guaranteed-priority regime — a VC packed with priority-0 work
    while priority-5 (preempting) and priority-0 arrivals alternate — is
    where every request used to alternate the view's parameter point
    (each guaranteed schedule trials OPPORTUNISTIC first), forcing a full
    fleet re-score + re-sort per request. Slots on vs off (the pre-slot
    single-view behavior) interleaved in one process, medians of reps.
    The differential proof that slots change no placement lives in
    tests/test_placement_equivalence.py."""
    from hivedscheduler_tpu.algorithm import placement

    t0 = time.perf_counter()

    def run_once(multi: bool) -> tuple:
        saved = placement.MULTI_SLOTS_DEFAULT
        placement.MULTI_SLOTS_DEFAULT = multi
        try:
            sched = HivedScheduler(
                build_config(cubes, slices, solos),
                kube_client=NullKubeClient(),
                auto_admit=True,
            )
        finally:
            placement.MULTI_SLOTS_DEFAULT = saved
        nodes = sched.core.configured_node_names()
        for n in nodes:
            sched.add_node(Node(name=n))
        # Pack the research VC's v5e quota with priority-0 singletons.
        g = 0
        while True:
            g += 1
            gname = f"fill{g}"
            group = {
                "name": gname,
                "members": [{"podNumber": 1, "leafCellNumber": 4}],
            }
            p = make_pod(
                f"{gname}-0", f"{gname}-u0", "research", 0,
                "v5e-chip", 4, group,
            )
            r = sched.filter_routine(
                ei.ExtenderArgs(pod=p, node_names=nodes)
            )
            if not r.node_names:
                sched.delete_pod(p)
                break
        # Alternate priority-5 (probe + release) and priority-0 arrivals.
        lat = []
        t_run = time.perf_counter()
        for k in range(arrivals):
            for prio, tag in ((5, "hi"), (0, "lo")):
                gname = f"{tag}{k}"
                group = {
                    "name": gname,
                    "members": [{"podNumber": 1, "leafCellNumber": 4}],
                }
                p = make_pod(
                    f"{gname}-0", f"{gname}-u0", "research", prio,
                    "v5e-chip", 4, group,
                )
                t1 = time.perf_counter()
                r = sched.filter_routine(
                    ei.ExtenderArgs(pod=p, node_names=nodes)
                )
                lat.append((time.perf_counter() - t1) * 1e3)
                if r.node_names:
                    sched.delete_pod(p)
        wall = time.perf_counter() - t_run
        p50, p99 = _percentiles(lat)
        return p50, p99, (2 * arrivals) / wall

    on_runs, off_runs = [], []
    for _ in range(reps):
        off_runs.append(run_once(False))
        on_runs.append(run_once(True))
    med = lambda runs, i: statistics.median(r[i] for r in runs)  # noqa: E731
    p50_on, p50_off = med(on_runs, 0), med(off_runs, 0)
    return _stage_meta({
        "arrivals": 2 * arrivals,
        "reps": reps,
        "slots_on": {
            "p50_ms": round(p50_on, 3),
            "p99_ms": round(med(on_runs, 1), 3),
            "req_per_sec": round(med(on_runs, 2), 1),
        },
        "slots_off": {
            "p50_ms": round(p50_off, 3),
            "p99_ms": round(med(off_runs, 1), 3),
            "req_per_sec": round(med(off_runs, 2), 1),
        },
        "p50_speedup": round(p50_off / p50_on, 2) if p50_on else 0.0,
    }, 16 * cubes + 4 * slices + solos, t0)


def bench_relist_ab(
    cubes: int = 64,
    slices: int = 160,
    solos: int = 64,
    relists: int = 5,
    reps: int = 3,
) -> dict:
    """Node-event no-op fast-path A/B at the 1728-host fleet: the cost of
    a no-change relist (an informer gap repair re-delivers EVERY node),
    and the filter p50 while such relists run concurrently — each
    no-change update used to take the global all-chains lock order,
    stalling every in-flight filter. One scheduler, fastpath toggled per
    rep (instance knob), interleaved."""
    import threading as _threading

    t0 = time.perf_counter()
    sched = HivedScheduler(
        build_config(cubes, slices, solos),
        kube_client=NullKubeClient(),
        auto_admit=True,
    )
    nodes = sched.core.configured_node_names()
    node_objs = {n: Node(name=n) for n in nodes}
    for n in nodes:
        sched.add_node(node_objs[n])

    def relist_once() -> float:
        t1 = time.perf_counter()
        for n in nodes:
            sched.update_node(node_objs[n], node_objs[n])
        return (time.perf_counter() - t1) * 1e3

    def filter_under_relist() -> tuple:
        # Periodic relists (a watch-cycle gap repair every 50 ms — far
        # denser than production, sized so several land inside the
        # measured window), not an unthrottled hot loop: the question is
        # how much one relist STALLS concurrent filters, not how fast a
        # spinning thread can burn the GIL.
        stop = _threading.Event()

        def storm():
            while not stop.is_set():
                relist_once()
                stop.wait(0.05)

        t = _threading.Thread(target=storm, daemon=True)
        t.start()
        try:
            def schedule_pod(p):
                r = sched.filter_routine(
                    ei.ExtenderArgs(pod=p, node_names=nodes)
                )
                return bool(r.node_names)

            lat, live, _ = _drive_gangs(
                sched, schedule_pod, 40, prefix=f"rl{time.monotonic_ns()}"
            )
        finally:
            stop.set()
            t.join()
        for _, old in live:
            for q in old:
                sched.delete_pod(q)
        return _percentiles(lat)

    relist_on, relist_off, lat_on, lat_off = [], [], [], []
    for _ in range(reps):
        sched.node_event_fastpath = False
        relist_off.extend(relist_once() for _ in range(relists))
        lat_off.append(filter_under_relist())
        sched.node_event_fastpath = True
        relist_on.extend(relist_once() for _ in range(relists))
        lat_on.append(filter_under_relist())
    noops = sched.get_metrics()["nodeEventNoopCount"]
    r_on = statistics.median(relist_on)
    r_off = statistics.median(relist_off)
    med = lambda runs, i: statistics.median(r[i] for r in runs)  # noqa: E731
    return _stage_meta({
        "reps": reps,
        "relists_per_rep": relists,
        "relist_ms_fastpath_on": round(r_on, 2),
        "relist_ms_fastpath_off": round(r_off, 2),
        "relist_speedup": round(r_off / r_on, 2) if r_on else 0.0,
        "filter_under_relist_on": {
            "p50_ms": round(med(lat_on, 0), 3),
            "p99_ms": round(med(lat_on, 1), 3),
        },
        "filter_under_relist_off": {
            "p50_ms": round(med(lat_off, 0), 3),
            "p99_ms": round(med(lat_off, 1), 3),
        },
        "node_event_noop_count": noops,
    }, 16 * cubes + 4 * slices + solos, t0)


def bench_sim(
    sizes=(432, 864, 1728),
    gangs_per_432: int = 120,
    seed: int = 0,
    duration_s: float = 1800.0,
) -> dict:
    """Trace-driven fleet-size trend (HIVED_BENCH_SIM=1): one seeded
    diurnal trace per fleet size through the real scheduler (sim tier,
    doc/hot-path.md "Warehouse-scale profile"), reporting the latency
    tail AND the scheduling-quality metrics per size — the trend lines
    ROADMAP new-direction 4 asked for. The 5k/10k/50k-host points run
    via ``python -m hivedscheduler_tpu.sim`` (too heavy for the default
    driver); this stage pins the CI-sized end of the same curves."""
    from hivedscheduler_tpu.sim.driver import run_trace
    from hivedscheduler_tpu.sim.trace import TraceShape, generate_trace

    t0 = time.perf_counter()
    curve: dict = {}
    for hosts in sizes:
        shape = TraceShape(
            hosts=hosts,
            gangs=max(20, int(gangs_per_432 * hosts / 432)),
            duration_s=duration_s,
            pattern="diurnal",
            fault_events=max(4, hosts // 100),
        )
        report = run_trace(generate_trace(seed, shape), mode="inproc")
        frag = report["fragmentation"] or {}
        pend = report.get("pendingPlane") or {}
        curve[str(report["hosts"])] = {
            "gangs": shape.gangs,
            "p50_ms": report["latency"]["p50Ms"],
            "p99_ms": report["latency"]["p99Ms"],
            "pods_per_sec": report["podsPerSec"],
            "preemption_rate": report["preemption"][
                "ratePerBoundGuaranteed"
            ],
            "quota_satisfaction": report["quotaSatisfaction"]["fraction"],
            "largest_free_slice_chips": frag.get(
                "largestFreeSliceChips", 0
            ),
            # Pending-pod plane (ISSUE 13 artifact-hygiene satellite):
            # the waiting-queue depth TREND (max + end of trace), not
            # just waitingAtEnd, plus the wait-cache hit ratio.
            "waiting_max": pend.get("waitingMax", 0),
            "waiting_at_end": pend.get("waitingAtEnd", 0),
            "wait_cache_hit_ratio": pend.get("waitCacheHitRatio", 0.0),
            "wall_s": report["wallS"],
        }
    return _stage_meta({
        "seed": seed,
        "pattern": "diurnal",
        "trend": curve,
    }, max(int(h) for h in curve) if curve else 0, t0)


def bench_pending(
    hosts: int = 216,
    gangs: int = 700,
    seed: int = 5,
    duration_s: float = 3600.0,
    mean_runtime_s: float = 3000.0,
    min_waiting: int = 200,
    storm_rounds: int = 20,
) -> dict:
    """Deep-pending-queue A/B (HIVED_BENCH_PENDING=1; ISSUE 13): one
    SATURATED trace — arrivals far outrunning capacity, so the waiting
    queue goes hundreds deep and every capacity-freeing event re-filters
    it — replayed at the IDENTICAL seed under three pending-plane modes:

    - ``indexed``  — the default: eligibility-indexed retry wakes +
      negative-filter cache;
    - ``cache``    — FIFO rescan of every waiter per event (the
      HIVED_SIM_FIFO_RETRY reference mode), wait cache ON: every
      unchanged re-filter answers from its rejection certificate;
    - ``baseline`` — FIFO rescan, wait cache OFF (the pre-ISSUE-13 cost
      profile, with the retry budget already retired from both sides).

    Each mode's replay is followed by a ``retry_storm`` sweep: the K8s
    default scheduler re-filters every pending pod on its backoff
    REGARDLESS of cluster events, so the storm re-filters the end-state
    waiting queue with NOTHING changed — the exact repeated-rejection
    regime the cache answers in O(1).

    The acceptance quantities (doc/hot-path.md "Pending-pod plane"):
    repeated-rejection re-filter throughput (storm attempts/second)
    ``cache`` vs ``baseline`` ≥ 2x, storm filter p99 reduced, and the
    placement fingerprint BIT-IDENTICAL across all three modes (the
    cached ≡ recomputed and indexed ≡ FIFO differential proofs at bench
    scale). The fingerprint equality is asserted (correctness); the
    perf gates are recorded, not asserted — a regime where the cache
    does not win is reported as an honest null, per the PR-9/PR-11
    discipline (the in-trace event-driven wake numbers below are such a
    null at CI scale: every wake follows a real state change, so the
    hit ratio is structurally low there)."""
    from hivedscheduler_tpu.sim.driver import run_trace
    from hivedscheduler_tpu.sim.report import placement_fingerprint
    from hivedscheduler_tpu.sim.trace import TraceShape, generate_trace

    t0 = time.perf_counter()
    shape = TraceShape(
        hosts=hosts,
        gangs=gangs,
        duration_s=duration_s,
        pattern="burst",
        burst_fraction=0.7,
        mean_runtime_s=mean_runtime_s,
        opportunistic_fraction=0.3,
        fault_events=max(8, hosts // 20),
    )
    trace = generate_trace(seed, shape)
    modes = (
        ("indexed", dict(fifo_retry=False, wait_cache=True)),
        ("cache", dict(fifo_retry=True, wait_cache=True)),
        ("baseline", dict(fifo_retry=True, wait_cache=False)),
    )
    reports = {
        name: run_trace(trace, retry_storm_rounds=storm_rounds, **kw)
        for name, kw in modes
    }

    def side(name: str) -> dict:
        r = reports[name]
        pend = r["pendingPlane"]
        wall = pend["wakeWallS"]
        storm = pend.get("retryStorm", {})
        return {
            "waiting_max": pend["waitingMax"],
            "waiting_by_key": pend.get("waitingByKey", {}),
            "wake_events": pend["wakeEvents"],
            "wake_attempts": pend["wakeAttempts"],
            "wake_skipped": pend["wakeSkipped"],
            "wake_wall_s": wall,
            "wake_refilter_per_sec": round(
                pend["wakeAttempts"] / wall, 1
            )
            if wall > 0
            else 0.0,
            "fast_wait_count": pend["fastWaitCount"],
            "wait_cache_hit_ratio": pend["waitCacheHitRatio"],
            "storm": storm,
            "bound_gangs": r["counts"]["boundGangs"],
        }

    out = {name: side(name) for name in reports}
    fps = {
        name: placement_fingerprint(r) for name, r in reports.items()
    }
    fingerprints_identical = (
        fps["indexed"] == fps["cache"] == fps["baseline"]
    )
    # The equivalence proofs are correctness, not perf: always asserted.
    assert fingerprints_identical, {
        n: r["counts"] for n, r in reports.items()
    }
    base, cache, idx = out["baseline"], out["cache"], out["indexed"]
    storm_speedup = (
        round(
            cache["storm"].get("refilterPerSec", 0.0)
            / base["storm"]["refilterPerSec"], 2
        )
        if base["storm"].get("refilterPerSec")
        else 0.0
    )
    return _stage_meta({
        "seed": seed,
        "gangs": gangs,
        "pattern": "burst",
        "deep_queue": base["waiting_max"] >= min_waiting,
        "min_waiting": min_waiting,
        "indexed": idx,
        "cache": cache,
        "baseline": base,
        "fingerprints_identical": fingerprints_identical,
        # Repeated-rejection throughput, cache on vs off, over the
        # identical end-state queue: the >=2x acceptance quantity.
        "refilter_speedup": storm_speedup,
        "refilter_speedup_gate": 2.0,
        "gate_met": storm_speedup >= 2.0,
        "storm_p99_reduced": (
            cache["storm"].get("steadyP99Ms", 0.0)
            < base["storm"].get("steadyP99Ms", 0.0)
        ),
        "wake_attempts_saved_by_index": (
            cache["wake_attempts"] - idx["wake_attempts"]
        ),
    }, hosts, t0)


def bench_defrag(
    hosts: int = 120,
    gangs: int = 500,
    seed: int = 11,
    duration_s: float = 3600.0,
    frag_samples: int = 16,
) -> dict:
    """Defragmenter A/B (HIVED_BENCH_DEFRAG=1; ISSUE 10): replay one
    long-running churn trace through the sim tier twice at the IDENTICAL
    seed — defragmenter off, then on (checkpoint-coordinated migrations
    executed at every fragmentation sample point) — and report the
    schedulable-slice-size distribution both ways. The acceptance
    quantity is the time-averaged largest free slice (bigger is better)
    and the count of stranded sub-host/sub-slice fragments (fewer is
    better); the stage asserts defrag never makes the distribution
    worse."""
    from hivedscheduler_tpu.sim.driver import run_trace
    from hivedscheduler_tpu.sim.trace import TraceShape, generate_trace

    t0 = time.perf_counter()
    shape = TraceShape(
        hosts=hosts,
        gangs=gangs,
        duration_s=duration_s,
        pattern="steady",
        mean_runtime_s=350.0,
        fault_events=8,
        opportunistic_fraction=0.35,
    )
    trace = generate_trace(seed, shape)
    reports = {
        tag: run_trace(
            trace, defrag=(tag == "on"), frag_samples=frag_samples
        )
        for tag in ("off", "on")
    }

    def dist(report: dict) -> dict:
        frag = report["fragmentation"] or {}
        series = frag.get("largestFreeSliceSeries") or [0]
        samples = frag.get("series") or []
        sub_host = [
            sum(v for k, v in s["freeSlices"].items() if int(k) < 4)
            for s in samples
        ] or [0]
        sub_slice = [
            sum(v for k, v in s["freeSlices"].items() if int(k) < 16)
            for s in samples
        ] or [0]
        return {
            "largest_free_slice_avg": round(sum(series) / len(series), 2),
            "largest_free_slice_end": series[-1],
            "sub_host_fragments_avg": round(
                sum(sub_host) / len(sub_host), 2
            ),
            "sub_slice_fragments_avg": round(
                sum(sub_slice) / len(sub_slice), 2
            ),
            "end_free_slices": frag.get("endFreeSlices", {}),
            "bound_gangs": report["counts"]["boundGangs"],
            "queue_wait_p50_s": report["quotaSatisfaction"][
                "queueWaitP50S"
            ],
        }

    off, on = dist(reports["off"]), dist(reports["on"])
    gain = round(
        on["largest_free_slice_avg"] - off["largest_free_slice_avg"], 2
    )
    migrations = reports["on"]["counts"]["defragMigrations"]
    # The A/B gate: at identical seed, defrag must never shrink the
    # schedulable-slice distribution (and improves it whenever its
    # migrations fire — the 60-second smoke asserts structure only).
    assert on["largest_free_slice_avg"] >= off["largest_free_slice_avg"], (
        off, on,
    )
    return _stage_meta({
        "seed": seed,
        "gangs": gangs,
        "pattern": "steady",
        "off": off,
        "on": on,
        "largest_free_slice_gain": gain,
        "proposals": reports["on"]["counts"]["defragProposals"],
        "migrations": migrations,
    }, hosts, t0)


def bench_whatif(
    hosts: int = 432,
    gangs: int = 1100,
    seed: int = 7,
    duration_s: float = 3600.0,
    mean_runtime_s: float = 2600.0,
    whatif_at: float = 0.5,
    min_waiting: int = 30,
    capacity_gangs: int = 80,
) -> dict:
    """Shadow what-if plane acceptance stage (HIVED_BENCH_WHATIF=1;
    doc/hot-path.md "Shadow what-if plane"): one seeded burst trace at
    the 432-host fleet, replayed twice at the IDENTICAL seed —

    - **baseline**: plain replay, recording every gang's ACTUAL bind
      time (the forecast's ground truth);
    - **instrumented**: the same replay, with a mid-trace what-if sample
      at ``whatif_at`` of trace time: the whole waiting queue is
      forecast against the known departure horizon on a snapshot fork,
      TWICE on independent forks (determinism verified in-stage), with
      the read-only audit armed.

    Asserted (correctness, not perf): the two replays' placement
    fingerprints are BIT-IDENTICAL (the forecast mutated nothing — the
    strongest no-live-mutation proof available), the double-run forecast
    lists are identical (determinism at one snapshot epoch), a forecast
    exists for EVERY waiting gang at the sample point, and a deliberate
    live mutation from inside a shadow section raises ShadowWriteError.

    Recorded (the honest quantities): median/mean |predicted - actual|
    wait error over the gangs that both got a schedule forecast and
    actually bound — the forecast knows the departure horizon but NOT
    the future arrivals, so late-trace submits landing ahead of a
    forecast gang push its actual bind later than promised; that error
    is structural and reported, not hidden (doc/hot-path.md). Plus
    forecast/fork wall costs and a capacity-planning run (tomorrow's
    trace against the end-of-trace snapshot, SLO risk out)."""
    from hivedscheduler_tpu.scheduler import whatif as whatif_mod
    from hivedscheduler_tpu.sim.driver import (
        TraceDriver, build_fleet_config,
    )
    from hivedscheduler_tpu.sim.report import placement_fingerprint
    from hivedscheduler_tpu.sim.trace import TraceShape, generate_trace

    t0 = time.perf_counter()
    shape = TraceShape(
        hosts=hosts,
        gangs=gangs,
        duration_s=duration_s,
        pattern="burst",
        burst_fraction=0.7,
        mean_runtime_s=mean_runtime_s,
        opportunistic_fraction=0.3,
        # No fault events: the forecast horizon carries departures only,
        # so the error attribution stays clean (unknown-arrival error is
        # the one structural term; doc/hot-path.md records it).
        fault_events=0,
    )
    trace = generate_trace(seed, shape)
    _, actual_hosts = build_fleet_config(hosts)

    base_driver = TraceDriver(build_fleet_config(hosts)[0])
    base_report = base_driver.run(trace)
    base_driver.close()
    inst_driver = TraceDriver(
        build_fleet_config(hosts)[0],
        whatif_at=whatif_at,
        whatif_verify=True,
    )
    inst_report = inst_driver.run(trace)
    sample = inst_driver.whatif_sample
    bound_t = dict(inst_driver.gang_bound_t)

    # -- correctness gates (always asserted) -------------------------- #
    fp_base = placement_fingerprint(base_report)
    fp_inst = placement_fingerprint(inst_report)
    assert fp_base == fp_inst, "what-if sample perturbed the live replay"
    assert sample is not None, "trace never crossed the sample point"
    assert sample["deterministic"] is True, (
        "forecast not deterministic across repeated forks"
    )
    forecasts = sample["forecasts"]
    assert len(forecasts) == sample["waitingCount"], (
        "a waiting gang got no forecast",
        len(forecasts), sample["waitingCount"],
    )
    plane = inst_driver.sched.whatif
    audit_caught = False
    try:
        with plane.shadow_section():
            inst_driver.sched.health_tick()  # a live mutator entry
    except whatif_mod.ShadowWriteError:
        audit_caught = True
    assert audit_caught, "read-only audit failed to fence a live mutator"

    # -- forecast-vs-actual error (recorded) -------------------------- #
    sample_t = sample["t"]
    errors = []
    predicted_never_bound = 0
    blocked_but_bound = 0
    for f in forecasts:
        actual = bound_t.get(f["gang"])
        if f["verdict"] == whatif_mod.VERDICT_SCHEDULE:
            if actual is None:
                predicted_never_bound += 1
                continue
            predicted_abs = sample_t + f["predictedWaitS"]
            errors.append(abs(predicted_abs - actual))
        elif actual is not None:
            blocked_but_bound += 1
    errors.sort()
    median_err = errors[len(errors) // 2] if errors else None
    mean_err = sum(errors) / len(errors) if errors else None

    # -- capacity planning: tomorrow's trace on today's snapshot ------- #
    cap_shape = TraceShape(
        hosts=hosts,
        gangs=capacity_gangs,
        duration_s=duration_s / 2,
        pattern="diurnal",
        mean_runtime_s=mean_runtime_s / 2,
        opportunistic_fraction=0.3,
        fault_events=0,
    )
    cap_trace = generate_trace(seed + 1, cap_shape)
    capacity = plane.serve(
        {"capacityTrace": cap_trace, "sloWaitS": 600.0}
    )

    meta = sample["meta"]
    n_forecast = max(1, len(forecasts))
    result = _stage_meta({
        "seed": seed,
        "gangs": gangs,
        "pattern": "burst",
        "sample_t": sample_t,
        "waiting_at_sample": sample["waitingCount"],
        "deep_queue": sample["waitingCount"] >= min_waiting,
        "min_waiting": min_waiting,
        "forecasts": len(forecasts),
        "schedule_verdicts": sum(
            1 for f in forecasts
            if f["verdict"] == whatif_mod.VERDICT_SCHEDULE
        ),
        "blocked_verdicts": sum(
            1 for f in forecasts
            if f["verdict"] == whatif_mod.VERDICT_BLOCKED
        ),
        "fingerprints_identical": True,   # asserted above
        "deterministic": True,            # asserted above
        "audit_caught": audit_caught,
        "matched": len(errors),
        "median_abs_error_s": (
            round(median_err, 1) if median_err is not None else None
        ),
        "mean_abs_error_s": (
            round(mean_err, 1) if mean_err is not None else None
        ),
        "predicted_schedule_never_bound": predicted_never_bound,
        "blocked_but_bound": blocked_but_bound,
        "fork_pods": meta["forkPods"],
        "fork_ms": meta["forkMs"],
        "forecast_ms": meta["forecastMs"],
        "per_gang_forecast_ms": round(
            meta["forecastMs"] / n_forecast, 3
        ),
        "capacity": {
            "slo_risk": capacity["sloRisk"],
            "forecast_ms": capacity["meta"]["forecastMs"],
        },
        "baseline_bound_gangs": base_report["counts"]["boundGangs"],
    }, actual_hosts, t0)
    inst_driver.close()
    return result


class _SnapshotKubeClient(NullKubeClient):
    """NullKubeClient + an in-memory snapshot ConfigMap family, for the
    recovery-blackout stage (the flusher needs somewhere to persist)."""

    def __init__(self) -> None:
        super().__init__()
        self.snapshot = None

    def persist_snapshot(self, chunks) -> None:
        self.snapshot = list(chunks)

    def load_snapshot(self):
        return list(self.snapshot) if self.snapshot is not None else None


def _drive_and_confirm(sched, nodes, n_gangs, shapes=None):
    """Drive gangs through filter AND confirm every assume-bind (the
    informer's MODIFIED-with-nodeName event, in miniature) so the cluster
    accumulates durable BOUND pods — what snapshots serialize and recovery
    replays."""

    def schedule_pod(p):
        r = sched.filter_routine(ei.ExtenderArgs(pod=p, node_names=nodes))
        if not r.node_names:
            return False
        bp = sched.pod_schedule_statuses[p.uid].pod
        confirmed = Pod(
            name=bp.name, namespace=bp.namespace, uid=bp.uid,
            annotations=dict(bp.annotations), node_name=bp.node_name,
            phase="Running", resource_limits=dict(bp.resource_limits),
        )
        # old = the original UNBOUND pod: update_pod's unbound->bound
        # branch is the informer confirm that flips BINDING -> BOUND.
        sched.update_pod(p, confirmed)
        return True

    return _drive_gangs(sched, schedule_pod, n_gangs, shapes=shapes)


def bench_recovery_blackout(
    cubes: int = 16,
    slices: int = 40,
    solos: int = 16,
    n_gangs: int = 1200,
    reps: int = 3,
    flusher_reps: int = 5,
    flusher_interval_s: float = 1.0,
) -> dict:
    """Recovery-blackout A/B at the 432-host fleet (ISSUE 7 acceptance):
    wall time to readiness for FULL annotation replay vs SNAPSHOT+DELTA
    recovery of the same crashed cluster (medians of ``reps``), plus the
    snapshot-flusher overhead A/B on the gang-schedule hot path: the
    flusher exports under the global guard and its full per-flush cost
    (walk + encode, ~23ms at this packed fleet) must stay <=3% of the
    filter p50 at a 1 Hz cadence — already 10-100x any sane production
    setting for multi-MB state snapshots (the per-pod record/JSON memo
    makes steady-state flushes O(changed), so production cadences cost
    well under 1%). Medians of ``flusher_reps`` interleaved on/off
    pairs, since the per-rep p50 is noisy at fleet scale.

    The fleet is packed with the pod-DENSE gang mix: recovery cost is per
    bound pod (one annotation decode + validation walk each,
    doc/hot-path.md), so the blackout regime the paper motivates (100k-pod
    fleets, minutes of blackout) is many small pods, not few large ones.

    Two snapshot numbers, one fleet:

    - ``snapshot_delta_ms`` (the headline, vs ``full_replay_ms``): a WARM
      takeover — the standby prefetched the chunk family on its standby
      beats (StandbyLoop.on_standby_beat -> prefetch_snapshot), so
      recovery restores the decoded projection verbatim and
      fingerprint-checks each live pod. This is the failover blackout the
      HA plane exists to shrink.
    - ``snapshot_cold_ms``: same snapshot, no prefetch — a plain restart
      that must also JSON-decode the snapshot inside the blackout window.
    """
    t0_stage = time.perf_counter()
    config_args = dict(cubes=cubes, slices=slices, solos=solos)
    client = _SnapshotKubeClient()
    sched = HivedScheduler(build_config(**config_args), kube_client=client)
    nodes = sched.core.configured_node_names()
    for n in nodes:
        sched.add_node(Node(name=n))
    sched.mark_ready()
    _drive_and_confirm(sched, nodes, n_gangs, shapes=DENSE_GANG_SHAPES)
    sched.note_watermark(1)
    assert sched.flush_snapshot_now(), "snapshot flush failed"
    snapshot_chunks = client.snapshot
    bound = [
        st.pod
        for st in sched.pod_schedule_statuses.values()
        if st.pod is not None and st.pod.node_name
    ]
    node_objs = [Node(name=n) for n in nodes]

    def recover_once(with_snapshot: bool, warm: bool = False):
        kube = _SnapshotKubeClient()
        if with_snapshot:
            kube.snapshot = list(snapshot_chunks)
        fresh = HivedScheduler(build_config(**config_args), kube_client=kube)
        if warm:
            # The standby's warm-up beat, OUTSIDE the blackout window: a
            # HOT standby decodes and pre-applies the projection into its
            # own core while standing by (__main__.on_standby_beat).
            assert fresh.prefetch_snapshot(min_watermark=0, apply=True)
        t0 = time.perf_counter()
        fresh.recover(node_objs, bound, min_watermark=0)
        return (time.perf_counter() - t0) * 1e3, fresh

    full_ms, cold_ms, snap_ms = [], [], []
    for _ in range(reps):
        ms, fresh = recover_once(False)
        assert fresh._recovery_mode == "full"
        full_ms.append(ms)
        ms, fresh = recover_once(True)
        assert fresh._recovery_mode == "snapshot+delta", (
            fresh._recovery_mode
        )
        cold_ms.append(ms)
        ms, fresh = recover_once(True, warm=True)
        assert fresh._recovery_mode == "snapshot+delta", (
            fresh._recovery_mode
        )
        assert len(fresh.pod_schedule_statuses) == len(bound)
        snap_ms.append(ms)
    full_med = statistics.median(full_ms)
    cold_med = statistics.median(cold_ms)
    snap_med = statistics.median(snap_ms)

    def p50_once(interval_s: float) -> float:
        # The flusher-overhead side runs the STANDARD gang mix (the same
        # hot path every other latency stage measures), not the dense
        # recovery mix — the question is what the flusher costs a normally
        # loaded scheduler. Collect before each rep so one rep's garbage
        # (the flusher churns MB-scale strings) never bills the next.
        gc.collect()
        kube = _SnapshotKubeClient()
        s = HivedScheduler(build_config(**config_args), kube_client=kube)
        for n in nodes:
            s.add_node(Node(name=n))
        s.mark_ready()
        s.note_watermark(1)
        if interval_s > 0:
            s.start_snapshot_flusher(interval_s)
        try:
            lat, _, _ = _drive_and_confirm(s, nodes, 240)
        finally:
            s.stop_snapshot_flusher()
        p50, _ = _percentiles(lat)
        return p50

    # Paired A/B: each rep measures flusher-on and flusher-off back to
    # back and contributes ONE overhead ratio; the reported overhead is
    # the median of the paired ratios. Pairing cancels the slow machine
    # drift that a ratio-of-medians design (bench_tracing_ab) leaves in —
    # at a ~2% true effect the drift otherwise dominates the verdict.
    on_p50s, off_p50s, pair_ratios = [], [], []
    for _ in range(flusher_reps):
        on = p50_once(flusher_interval_s)
        off = p50_once(0.0)
        on_p50s.append(on)
        off_p50s.append(off)
        if off > 0:
            pair_ratios.append(on / off)
    on_med = statistics.median(on_p50s)
    off_med = statistics.median(off_p50s)
    ratio_med = statistics.median(pair_ratios) if pair_ratios else 1.0
    return _stage_meta({
        "fleet_hosts": 16 * cubes + 4 * slices + solos,
        "pods_recovered": len(bound),
        "full_replay_ms": round(full_med, 2),
        "snapshot_delta_ms": round(snap_med, 2),
        "snapshot_cold_ms": round(cold_med, 2),
        "full_replay_per_pod_ms": round(full_med / max(1, len(bound)), 4),
        "snapshot_delta_per_pod_ms": round(
            snap_med / max(1, len(bound)), 4
        ),
        "speedup": round(full_med / snap_med, 2) if snap_med else 0.0,
        "speedup_cold": round(full_med / cold_med, 2) if cold_med else 0.0,
        "speedup_budget": 5.0,  # acceptance: snapshot+delta >= 5x faster
        "flusher_ab": {
            "interval_s": flusher_interval_s,
            "p50_on_ms": round(on_med, 3),
            "p50_off_ms": round(off_med, 3),
            "overhead_pct": round((ratio_med - 1.0) * 100.0, 2),
            "budget_pct": 3.0,
        },
    }, 16 * cubes + 4 * slices + solos, t0_stage)


def bench_store(
    cubes: int = 26,
    slices: int = 2,
    solos: int = 8,
    n_gangs: int = 1200,
    reps: int = 3,
    store_reps: int = 5,
) -> dict:
    """Durable-state plane v2 acceptance stage (HIVED_BENCH_STORE=1;
    hack/soak.sh --store): the partial-fallback recovery A/B at the
    432-host fleet, plus the object-store backend's persist/load wall.

    The A/B runs BOTH arms behind a hot standby (prefetch + pre-apply on
    an idle beat, OUTSIDE the timed blackout window — the same warm
    headline bench_recovery_blackout reports): flush the sectioned v3
    envelope, corrupt EXACTLY ONE chain-family section (a bit flip at
    the manifest-computed byte offset — the same arithmetic decode
    runs), then take over. v2's all-or-nothing envelope would throw the
    whole snapshot away and replay every annotation; v3 pre-applies the
    healthy families on the standby beat and the takeover replays only
    the corrupt family's chains — asserted in-stage to land in
    ``snapshot+partial`` with a placement fingerprint identical to BOTH
    a full annotation replay and a never-corrupted snapshot+delta
    shadow. The corrupt section is the family with the FEWEST bound
    pods: the localized fault the sectioned schema exists for (one
    rotted object out of many), on the asymmetric fleet shape where
    blast radius actually is proportional — the default 432 hosts put
    416 under the v5p family and 16 under v5e. Acceptance: partial
    fallback >= 3x faster than the full replay (``speedup_gate``;
    medians of ``reps``; recorded as ``gate_passed``). Honest nulls
    live in doc/hot-path.md: a COLD partial restore is decode-dominated
    and can lose to the full replay outright at MB-scale envelopes, and
    corrupting the LARGEST family degrades toward full-replay cost by
    design.

    The store side times :class:`FileSnapshotStore` persist (chunk
    writes + fsync + atomic manifest flip + generation GC) and load for
    the same envelope, and checks GC holds exactly the configured
    generation count — the cost of taking snapshots off the apiserver.
    """
    import shutil
    import tempfile

    from hivedscheduler_tpu.algorithm.cell import LOWEST_LEVEL
    from hivedscheduler_tpu.scheduler import snapshot as snapshot_mod
    from hivedscheduler_tpu.scheduler.store import FileSnapshotStore

    def physical_fingerprint(s) -> str:
        """Placement-equivalence fingerprint over the PHYSICAL side: leaf
        cell states/owners, the free set, and per-pod placements. Virtual
        cell identity within a level is interchangeable (the chaos
        plane's equivalence relation), so the snapshot's virtual-binding
        labels vs a replay's fresh labels must not count as divergence."""
        leaves = {
            leaf.address: (
                leaf.state.value, leaf.priority, leaf.healthy,
                leaf.draining,
                leaf.using_group.name if leaf.using_group else None,
            )
            for ccl in s.core.full_cell_list.values()
            for leaf in ccl[LOWEST_LEVEL]
        }
        free = {
            str(chain): {
                lvl: sorted(c.address for c in cl)
                for lvl, cl in ccl.levels.items() if len(cl)
            }
            for chain, ccl in sorted(s.core.free_cell_list.items())
        }
        pods = sorted(
            (uid, st.pod.node_name)
            for uid, st in s.pod_schedule_statuses.items()
            if st.pod is not None
        )
        return json.dumps(
            {"leaves": leaves, "free": free, "pods": pods},
            sort_keys=True, default=str,
        )

    t0_stage = time.perf_counter()
    config_args = dict(cubes=cubes, slices=slices, solos=solos)
    client = _SnapshotKubeClient()
    sched = HivedScheduler(build_config(**config_args), kube_client=client)
    nodes = sched.core.configured_node_names()
    for n in nodes:
        sched.add_node(Node(name=n))
    sched.mark_ready()
    _drive_and_confirm(sched, nodes, n_gangs, shapes=DENSE_GANG_SHAPES)
    sched.note_watermark(1)
    assert sched.flush_snapshot_now(), "snapshot flush failed"
    clean_chunks = list(client.snapshot)

    # Corrupt the chain family with the FEWEST bound pods, located by
    # manifest byte offsets: recovery cost is proportional to the
    # damaged family's pod share, so this is the scenario the sectioned
    # schema buys the most on — and corrupting the LARGEST family
    # degrades toward full-replay cost by design (doc/hot-path.md
    # records that honest null).
    snap, _reason = snapshot_mod.decode(
        clean_chunks, sched._config_fingerprint, None
    )
    assert snap is not None, _reason
    fam_pods = {
        f["name"]: len(f.get("pods") or ())
        for f in snap.get("_families") or ()
    }
    assert fam_pods, "no chain-family sections in the envelope"
    target_name = min(fam_pods, key=fam_pods.get)
    manifest = json.loads(clean_chunks[0])
    offset = 0
    target = None
    for entry in manifest["sections"]:
        if entry.get("name") == target_name:
            target = (entry, offset)
        offset += entry["bytes"]
    assert target is not None, (target_name, manifest["sections"])
    entry, start = target
    body = "".join(clean_chunks[1:])
    pos = start + entry["bytes"] // 2
    corrupt_body = (
        body[:pos] + ("X" if body[pos] != "X" else "Y") + body[pos + 1:]
    )
    corrupt_chunks = [clean_chunks[0], corrupt_body]

    bound = [
        st.pod
        for st in sched.pod_schedule_statuses.values()
        if st.pod is not None and st.pod.node_name
    ]
    node_objs = [Node(name=n) for n in nodes]

    def recover_once(chunks):
        # Every arm gets the same hot-standby treatment: the prefetch
        # beat (decode + pre-apply; scoped to the healthy families when
        # the envelope is corrupt) runs BEFORE the timed window, like a
        # standby that was idling when the leader died. The full-replay
        # arm's standby finds nothing to warm — a lost envelope leaves
        # nothing to pre-apply — so its blackout carries the whole
        # annotation replay.
        kube = _SnapshotKubeClient()
        if chunks is not None:
            kube.snapshot = list(chunks)
        fresh = HivedScheduler(build_config(**config_args), kube_client=kube)
        fresh.prefetch_snapshot(min_watermark=0, apply=True)
        t0 = time.perf_counter()
        fresh.recover(node_objs, bound, min_watermark=0)
        return (time.perf_counter() - t0) * 1e3, fresh

    full_ms, partial_ms = [], []
    replayed_sections = 0
    shadow = partial = clean = None
    for _ in range(reps):
        ms, shadow = recover_once(None)
        assert shadow._recovery_mode == "full"
        full_ms.append(ms)
        ms, partial = recover_once(corrupt_chunks)
        assert partial._recovery_mode == "snapshot+partial", (
            partial._recovery_mode
        )
        m = partial.get_metrics()
        assert m["snapshotSectionFallbackCount"] >= 1
        replayed_sections = m["snapshotSectionFallbackCount"]
        partial_ms.append(ms)
    _, clean = recover_once(clean_chunks)
    assert clean._recovery_mode == "snapshot+delta", clean._recovery_mode

    # The differential: partial fallback must be INVISIBLE in the landed
    # state — identical to the full replay AND the never-corrupted
    # snapshot shadow, pod set included.
    fp_partial = physical_fingerprint(partial)
    assert fp_partial == physical_fingerprint(shadow), (
        "partial fallback diverged from full replay"
    )
    assert fp_partial == physical_fingerprint(clean), (
        "partial fallback diverged from the never-corrupted shadow"
    )
    assert (
        set(partial.pod_schedule_statuses)
        == set(shadow.pod_schedule_statuses)
        == set(clean.pod_schedule_statuses)
    )

    # Object-store wall: persist (chunk writes + fsync + atomic flip +
    # GC) and load of the same envelope, with GC holding exactly N.
    keep = 3
    store_dir = tempfile.mkdtemp(prefix="hived-bench-store-")
    try:
        store = FileSnapshotStore(store_dir, keep_generations=keep)
        persist_ms, load_ms = [], []
        for _ in range(max(store_reps, keep + 1)):
            t0 = time.perf_counter()
            store.persist(clean_chunks)
            persist_ms.append((time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            loaded = store.load()
            load_ms.append((time.perf_counter() - t0) * 1e3)
        assert loaded == clean_chunks, "store round-trip mismatch"
        on_disk = [
            n for n in os.listdir(store_dir) if n.startswith("gen-")
        ]
        assert len(on_disk) == keep, on_disk
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)

    full_med = statistics.median(full_ms)
    partial_med = statistics.median(partial_ms)
    return _stage_meta({
        "fleet_hosts": 16 * cubes + 4 * slices + solos,
        "pods_recovered": len(bound),
        "snapshot_bytes": sum(len(c) for c in clean_chunks),
        "family_sections": sum(
            1 for s in manifest["sections"] if s.get("chains")
        ),
        "corrupt_section_bytes": entry["bytes"],
        "corrupt_family_pods": fam_pods[target_name],
        "replayed_sections": replayed_sections,
        "warm_standby": True,  # prefetch+pre-apply outside the window
        "full_replay_ms": round(full_med, 2),
        "partial_fallback_ms": round(partial_med, 2),
        "partial_speedup": (
            round(full_med / partial_med, 2) if partial_med else 0.0
        ),
        "speedup_gate": 3.0,  # acceptance: partial >= 3x full replay
        "gate_passed": bool(
            partial_med and full_med / partial_med >= 3.0
        ),
        "store_persist_ms": round(statistics.median(persist_ms), 3),
        "store_load_ms": round(statistics.median(load_ms), 3),
        "store_gc_kept": keep,
    }, 16 * cubes + 4 * slices + solos, t0_stage)


def bench_recovery(sched) -> dict:
    """Full restart recovery: rebuild a fresh scheduler purely from the
    bound pods' annotations (the informer replay path), timed end-to-end —
    the reference's work-preserving restart story (SURVEY §5)."""
    t0_stage = time.perf_counter()
    bound = [
        st.pod
        for st in sched.pod_schedule_statuses.values()
        if st.pod is not None and st.pod.node_name
    ]
    nodes = sched.core.configured_node_names()
    t0 = time.perf_counter()
    fresh = HivedScheduler(build_config(), kube_client=NullKubeClient())
    for n in nodes:
        fresh.add_node(Node(name=n))
    for bp in bound:
        bp2 = Pod(
            name=bp.name, namespace=bp.namespace, uid=bp.uid,
            annotations=bp.annotations, node_name=bp.node_name,
            phase="Running", resource_limits=bp.resource_limits,
        )
        fresh.add_pod(bp2)
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    return _stage_meta({
        "replay_total_ms": round(elapsed_ms, 2),
        "pods_replayed": len(bound),
        "replay_per_pod_ms": round(elapsed_ms / max(1, len(bound)), 3),
    }, 104, t0_stage)


def bench_http(n_gangs: int = 60) -> dict:
    """Wire-level gang-schedule latency: the same fleet and gang mix as
    ``run()``, but every filter call crosses a real HTTP hop — JSON encode
    of the ~96-node ExtenderArgs, TCP, server-side decode, the routine, and
    response decode are all inside the timed window. This is the path the
    10 ms budget actually applies to (the reference's extender is called
    over HTTP with a 5 s httpTimeout; the in-process p50 excludes the codec
    and socket cost)."""
    import http.client

    from hivedscheduler_tpu.webserver.server import WebServer

    t0_stage = time.perf_counter()
    sched = HivedScheduler(build_config(), kube_client=NullKubeClient())
    nodes = sched.core.configured_node_names()
    for n in nodes:
        sched.add_node(Node(name=n))
    ws = WebServer(sched, address="127.0.0.1:0")
    ws.start()
    try:
        class NoDelayConnection(http.client.HTTPConnection):
            """Client side of the same Nagle/delayed-ACK fix as the
            server's disable_nagle_algorithm (Go's net/http sets both by
            default). Set in connect() so the option survives the
            transparent auto-reconnects http.client performs when the
            server closes a keep-alive connection."""

            def connect(self):
                super().connect()
                self.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )

        conn = NoDelayConnection("127.0.0.1", ws.port)
        headers = {"Content-Type": "application/json"}
        def schedule_pod(p):
            body = json.dumps(
                ei.ExtenderArgs(pod=p, node_names=nodes).to_dict()
            )
            conn.request("POST", constants.FILTER_PATH, body, headers)
            resp = json.loads(conn.getresponse().read())
            return bool(resp.get("NodeNames"))

        # Warm-up: one request for an UNINFORMED pod — exercises TCP setup,
        # JSON codec, and handler dispatch through the same path as the
        # measured calls, returns an in-band error, and changes no
        # scheduler state; the first measured gang then pays only its own
        # cost.
        schedule_pod(
            make_pod("warm-0", "warm-u0", "prod", 0, "v5e-chip", 1, None)
        )

        lat, _, _ = _drive_gangs(sched, schedule_pod, n_gangs, prefix="h")
        conn.close()
        p50, p99 = _percentiles(lat)
        return _stage_meta({
            "http_gang_p50_ms": round(p50, 3),
            "http_gang_p99_ms": round(p99, 3),
            "gangs_scheduled": len(lat),
        }, 104, t0_stage)
    finally:
        ws.stop()


def _probe_timeout() -> int:
    """HIVED_BENCH_PROBE_TIMEOUT, degraded to the 300 s default on an
    unparseable value — the module's degrade-never-crash contract applies
    to env knobs too (a typo'd override must not abort the whole driver
    bench)."""
    try:
        t = int(os.environ.get("HIVED_BENCH_PROBE_TIMEOUT", "300"))
        return t if t > 0 else 300
    except ValueError:
        return 300


def model_perf() -> dict:
    """tokens/sec/chip + MFU on the default JAX backend (the real TPU when
    the driver runs this), via a subprocess with a hard timeout: a dead TPU
    tunnel hangs jax.devices() forever, and that must degrade to a skipped
    stage, not a hung benchmark. Keeps jax out of this process entirely."""
    here = os.path.dirname(os.path.abspath(__file__))
    # Fast probe first: a dead tunnel hangs backend init indefinitely, and
    # wasting the full perf timeout on it would risk the whole bench run.
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            capture_output=True,
            text=True,
            # 300 s: a healthy-but-slow tunnel was measured taking >120 s
            # to answer backend init on a loaded 1-core host; a dead one
            # hangs far past any timeout, so the extra patience only costs
            # the genuinely-dead case.
            timeout=_probe_timeout(),
            cwd=here,
        )
    except subprocess.TimeoutExpired:
        return _attach_sizing(_skip("backend probe timed out (TPU tunnel dead?)"))
    if probe.returncode != 0:
        return _attach_sizing(_skip(f"backend probe rc={probe.returncode}"))
    def attempt(extra_env: dict) -> dict:
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "hivedscheduler_tpu.models.perf"],
                capture_output=True,
                text=True,
                # Remote (tunnel) compiles of the Pallas train step + the
                # 8k XLA attention reference are minutes each; 600 s was
                # measured too tight for the full flash run.
                timeout=1500,
                cwd=here,
                env={**os.environ, **extra_env},
            )
        except subprocess.TimeoutExpired:
            return _skip("model perf timed out")
        if proc.returncode != 0:
            return _skip(f"rc={proc.returncode}: {proc.stderr[-300:]}")
        try:
            return json.loads(proc.stdout.strip().splitlines()[-1])
        except (json.JSONDecodeError, IndexError):
            return _skip(f"unparseable output: {proc.stdout[-200:]}")

    result = attempt({})
    if (
        "skipped" in result
        and "timed out" not in result["skipped"]
        and os.environ.get("HIVED_DISABLE_PALLAS", "0") != "1"
    ):
        # Degradation path: a hard CRASH in the Pallas kernels (e.g. a Mosaic
        # compiler abort the in-process fallback can't catch) must downgrade
        # the tokens/sec number to the XLA path, never erase it. Soft
        # failures never reach here: perf.py reports them as data (exit 0,
        # "train_error" keys) after its own single in-process retry, so one
        # persistent non-Pallas failure costs at most two runs total.
        # The optional stages are flash-kernel evidence and (long-context
        # especially) quadratic-cost on the XLA path — disable them so the
        # salvage retry fits the subprocess timeout; its job is one
        # tokens/sec number.
        retry = attempt({"HIVED_DISABLE_PALLAS": "1",
                         "HIVED_PERF_LONGCTX": "0", "HIVED_PERF_ZOO": "0",
                         "HIVED_PERF_DECODE": "0"})
        if "skipped" not in retry:
            # No _merge_carried: gluing flash-kernel sweep rows onto an
            # XLA-fallback headline would overstate the degraded run.
            retry["attention_fallback"] = "xla"
            retry["attention_fallback_reason"] = result["skipped"]
            return _attach_sizing(retry)
    if "attention_fallback" not in result:
        result = _merge_carried(result)
    return _attach_sizing(result)


if __name__ == "__main__":
    if os.environ.get("HIVED_BENCH_BOOT") == "1":
        # Boot ladder A/B (doc/hot-path.md "Boot and transport plane");
        # HIVED_BENCH_BOOT_50K=1 adds the measured 50k rung
        # (hack/soak.sh --boot-profile). Smoke sizing for CI:
        # HIVED_BENCH_BOOT_SMOKE=1 runs one small rung, no reps.
        if os.environ.get("HIVED_BENCH_BOOT_SMOKE") == "1":
            result = bench_boot(ladder=(432, 864), reps=1)
        else:
            result = bench_boot()
        print(
            json.dumps(
                {
                    "metric": "boot_speedup_10k",
                    "value": result["speedup_10k"],
                    "unit": "x",
                    "vs_baseline": round(
                        result["speedup_10k"] / result["speedup_gate"], 3
                    ),
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_STORE") == "1":
        # Durable-state plane v2 (doc/fault-model.md): partial-fallback
        # recovery A/B + object-store wall (hack/soak.sh --store).
        # Smoke sizing for CI: HIVED_BENCH_STORE_SMOKE=1 (tiny fleet;
        # wiring, not the perf gate).
        if os.environ.get("HIVED_BENCH_STORE_SMOKE") == "1":
            result = bench_store(
                cubes=2, slices=4, solos=2, n_gangs=60,
                reps=1, store_reps=2,
            )
        else:
            result = bench_store()
        print(
            json.dumps(
                {
                    "metric": "partial_fallback_speedup",
                    "value": result["partial_speedup"],
                    "unit": "x",
                    "vs_baseline": round(
                        result["partial_speedup"]
                        / result["speedup_gate"], 3
                    ),
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_RING") == "1":
        result = bench_ring_ab()
        print(
            json.dumps(
                {
                    "metric": "shard_ring_filter_p50",
                    "value": result["ring_p50_ms"],
                    "unit": "ms",
                    "vs_baseline": round(
                        result["ring_p50_ms"] / max(
                            result["pipe_p50_ms"], 1e-9
                        ), 3
                    ),
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_WIRE") == "1":
        # One-wire A/B (doc/hot-path.md "One wire"): binary frames +
        # delta suggested sets vs the HIVED_WIRE=0 legacy pickle path.
        # Smoke sizing for CI: HIVED_BENCH_WIRE_SMOKE=1 (432-host fleet).
        if os.environ.get("HIVED_BENCH_WIRE_SMOKE") == "1":
            result = bench_wire_ab(
                hosts_per_family=108, reps=2, calls=24, churn_calls=12
            )
        else:
            result = bench_wire_ab()
        print(
            json.dumps(
                {
                    "metric": "wire_churn_bytes_ratio",
                    "value": result["churn_bytes_ratio"],
                    "unit": "x",
                    "vs_baseline": result["steady_p50_ratio"],
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_SIM") == "1":
        # Standalone fleet-size trend stage (the default driver run
        # includes the same stage in its extra payload).
        result = bench_sim()
        largest = result["trend"][str(result["hosts"])]
        print(
            json.dumps(
                {
                    "metric": "sim_trace_p50_latency",
                    "value": largest["p50_ms"],
                    "unit": "ms",
                    "vs_baseline": round(
                        largest["p50_ms"] / TARGET_P50_MS, 3
                    ),
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_PENDING") == "1":
        # Pending-pod plane A/B (doc/hot-path.md "Pending-pod plane"):
        # deep-queue saturated trace, three modes at identical seed.
        # Smoke sizing for CI: HIVED_BENCH_PENDING_SMOKE=1.
        if os.environ.get("HIVED_BENCH_PENDING_SMOKE") == "1":
            result = bench_pending(
                hosts=104, gangs=200, duration_s=1800.0,
                mean_runtime_s=700.0, min_waiting=12,
            )
        else:
            result = bench_pending()
        print(
            json.dumps(
                {
                    "metric": "pending_refilter_speedup",
                    "value": result["refilter_speedup"],
                    "unit": "x",
                    "vs_baseline": round(
                        result["refilter_speedup"]
                        / result["refilter_speedup_gate"], 3
                    ),
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_WHATIF") == "1":
        # Shadow what-if plane acceptance (doc/hot-path.md "Shadow
        # what-if plane"); smoke sizing: HIVED_BENCH_WHATIF_SMOKE=1.
        if os.environ.get("HIVED_BENCH_WHATIF_SMOKE") == "1":
            result = bench_whatif(
                hosts=104, gangs=160, duration_s=1800.0,
                mean_runtime_s=700.0, min_waiting=2, capacity_gangs=24,
            )
        else:
            result = bench_whatif()
        print(
            json.dumps(
                {
                    "metric": "whatif_median_abs_error_s",
                    "value": result["median_abs_error_s"],
                    "unit": "s",
                    "vs_baseline": result["median_abs_error_s"],
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_DEFRAG") == "1":
        result = bench_defrag()
        print(
            json.dumps(
                {
                    "metric": "defrag_largest_free_slice_gain",
                    "value": result["largest_free_slice_gain"],
                    "unit": "chips",
                    "vs_baseline": result["largest_free_slice_gain"],
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_VIEW_SLOTS") == "1":
        run(n_gangs=24)  # warm-up
        result = bench_view_slots_ab()
        print(
            json.dumps(
                {
                    "metric": "view_slots_p50_speedup",
                    "value": result["p50_speedup"],
                    "unit": "x",
                    "vs_baseline": result["p50_speedup"],
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_RELIST") == "1":
        result = bench_relist_ab()
        print(
            json.dumps(
                {
                    "metric": "relist_noop_speedup",
                    "value": result["relist_speedup"],
                    "unit": "x",
                    "vs_baseline": result["relist_speedup"],
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_TRACE") == "1":
        # Standalone tracing-overhead gate (the default driver run includes
        # the same stage in its extra payload).
        run(n_gangs=24)  # warm-up
        result = bench_tracing_ab()
        print(
            json.dumps(
                {
                    "metric": "tracing_overhead_pct",
                    "value": result["overhead_pct"],
                    "unit": "%",
                    "vs_baseline": round(
                        result["overhead_pct"] / result["budget_pct"], 3
                    ),
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_CONCURRENT") == "1":
        try:
            conc_threads = int(
                os.environ.get("HIVED_BENCH_CONCURRENT_THREADS", "4")
            )
        except ValueError:
            conc_threads = 4
        if conc_threads <= 0:
            conc_threads = 4
        result = bench_concurrent(threads=conc_threads)
        print(
            json.dumps(
                {
                    "metric": "concurrent_filter_pods_per_sec",
                    "value": result["sharded"]["pods_per_sec"],
                    "unit": "pods/s",
                    "vs_baseline": result["speedup_vs_global_lock"],
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_PROCS") == "1":
        result = bench_procs()
        result["fleet_sweep"] = bench_fleet_sweep()
        # Core-scaled gate: the >=2.5x acceptance number presumes the
        # 4 workers + routing parent each get a core; on smaller hosts
        # the stage reports the measured curve and the ceiling instead
        # of asserting a physical impossibility.
        cores = os.cpu_count() or 1
        target = 2.5 if cores >= 5 else None
        result["speedup_target"] = target
        if target is not None:
            assert result["best_speedup_vs_inproc"] >= target, result
        print(
            json.dumps(
                {
                    "metric": "procs_filter_pods_per_sec",
                    "value": result["curve"][
                        str(result["best_shard_count"])
                    ]["pods_per_sec"],
                    "unit": "pods/s",
                    "vs_baseline": result["best_speedup_vs_inproc"],
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_RECOVERY") == "1":
        # Standalone recovery-blackout gate (the default driver run
        # includes the same stage in its extra payload).
        run(n_gangs=24)  # warm-up
        result = bench_recovery_blackout()
        print(
            json.dumps(
                {
                    "metric": "recovery_blackout_speedup",
                    "value": result["speedup"],
                    "unit": "x",
                    "vs_baseline": round(
                        result["speedup"] / result["speedup_budget"], 3
                    ),
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_AUDIT") == "1":
        # Black-box plane A/B (doc/hot-path.md "Black-box plane"):
        # auditor/recorder overhead at the 432-host fleet vs the ≤3%
        # budget + the capture→replay fingerprint assertion. Smoke
        # sizing: HIVED_BENCH_AUDIT_SMOKE=1.
        if os.environ.get("HIVED_BENCH_AUDIT_SMOKE") == "1":
            result = bench_audit(
                cubes=4, slices=10, solos=4, n_gangs=60, reps=1,
                replay_hosts=104, replay_gangs=100,
                frontend_families=2, frontend_hosts_per_family=8,
                frontend_reps=1,
            )
        else:
            result = bench_audit()
        print(json.dumps({
            "metric": "blackbox_overhead_pct",
            "value": result["overhead_pct"],
            "unit": "%",
            "vs_baseline": result["overhead_pct"] / 3.0
            if result["overhead_pct"] > 0 else 0.0,
            "extra": result,
        }))
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_SUPERVISE") == "1":
        # Shard supervision plane acceptance (doc/fault-model.md "Shard
        # supervision plane"): SIGKILL one worker mid-load at the
        # 432-host proc fleet; degraded admission and zero-loss
        # resurrection are asserted inside the stage, the surviving-p99
        # isolation gate is core-scaled (4 workers + routing parent each
        # need a core). Smoke sizing: HIVED_BENCH_SUPERVISE_SMOKE=1.
        if os.environ.get("HIVED_BENCH_SUPERVISE_SMOKE") == "1":
            result = bench_supervise(
                n_shards=2, families=2, hosts_per_family=8,
                warm_calls=6, steady_calls=30, degraded_calls=30,
                bind_gangs_per_family=2,
            )
        else:
            result = bench_supervise()
        cores = os.cpu_count() or 1
        if cores >= 5:
            assert result["within_budget"], result
        print(json.dumps({
            "metric": "supervise_surviving_p99_delta_pct",
            "value": result["surviving_p99_delta_pct"],
            "unit": "%",
            "vs_baseline": (
                result["surviving_p99_delta_pct"]
                / result["p99_budget_pct"]
                if result["surviving_p99_delta_pct"] > 0 else 0.0
            ),
            "extra": result,
        }))
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_OUTAGE") == "1":
        # Control-plane weather plane acceptance (doc/fault-model.md
        # "Control-plane weather plane"): full apiserver blackout struck
        # mid-load at the 432-host fleet. Zero 500s, write-behind
        # accounting, and post-drain convergence are asserted inside the
        # stage; the degraded-filter p99 gate is core-scaled like the
        # other latency budgets. Smoke sizing: HIVED_BENCH_OUTAGE_SMOKE=1.
        if os.environ.get("HIVED_BENCH_OUTAGE_SMOKE") == "1":
            result = bench_outage(
                cubes=2, slices=2, solos=2, n_gangs=40,
                warm_calls=6, steady_calls=30, degraded_calls=30,
                journal_writes=16, parked_binds=4,
            )
        else:
            result = bench_outage()
        cores = os.cpu_count() or 1
        if cores >= 3:
            assert result["within_budget"], result
        print(json.dumps({
            "metric": "outage_degraded_p99_delta_pct",
            "value": result["degraded_p99_delta_pct"],
            "unit": "%",
            "vs_baseline": (
                result["degraded_p99_delta_pct"]
                / result["p99_budget_pct"]
                if result["degraded_p99_delta_pct"] > 0 else 0.0
            ),
            "extra": result,
        }))
        sys.exit(0)
    if os.environ.get("HIVED_BENCH_SMOKE") == "1":
        try:
            smoke_gangs = int(os.environ.get("HIVED_BENCH_SMOKE_GANGS", "24"))
        except ValueError:
            smoke_gangs = 24
        if smoke_gangs <= 0:
            # Degrade-never-crash, like _probe_timeout: a zero/negative
            # override would hand statistics.median an empty sample.
            smoke_gangs = 24
        run(n_gangs=8)  # warm-up
        result = smoke(smoke_gangs)
        print(
            json.dumps(
                {
                    "metric": "gang_schedule_p50_latency_smoke",
                    "value": result["gang_schedule_p50_ms"],
                    "unit": "ms",
                    "vs_baseline": round(
                        result["gang_schedule_p50_ms"] / TARGET_P50_MS, 3
                    ),
                    "extra": result,
                }
            )
        )
        sys.exit(0)
    # Warm-up pass (imports, allocator caches), then the measured pass.
    run(n_gangs=24)
    p50, p99, n, sched, live, pods_per_sec = run()
    nodes = sched.core.configured_node_names()
    preempt_p50 = bench_preempt(sched, nodes)
    recovery = bench_recovery(sched)
    recovery_blackout = bench_recovery_blackout()
    http_stats = bench_http()
    tracing_ab = bench_tracing_ab()
    procs_stage = bench_procs()
    procs_stage["fleet_sweep"] = bench_fleet_sweep()
    view_slots_ab = bench_view_slots_ab()
    relist_ab = bench_relist_ab()
    sim_stage = bench_sim()
    pending_stage = bench_pending()
    defrag_stage = bench_defrag()
    boot_stage = bench_boot()
    ring_ab = bench_ring_ab()
    audit_stage = bench_audit()
    perf = model_perf()
    print(
        json.dumps(
            {
                "metric": "gang_schedule_p50_latency",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(p50 / TARGET_P50_MS, 3),
                "extra": {
                    "p99_ms": round(p99, 3),
                    "gangs_scheduled": n,
                    "filter_throughput_pods_per_sec": round(pods_per_sec, 1),
                    "preempt_p50_ms": round(preempt_p50, 3),
                    "recovery": recovery,
                    "recovery_blackout": recovery_blackout,
                    "http": http_stats,
                    "tracing_ab": tracing_ab,
                    "procs": procs_stage,
                    "view_slots_ab": view_slots_ab,
                    "relist_ab": relist_ab,
                    "sim": sim_stage,
                    "pending": pending_stage,
                    "defrag": defrag_stage,
                    "boot": boot_stage,
                    "ring_ab": ring_ab,
                    "audit_ab": audit_stage,
                    "model_perf": perf,
                },
            }
        )
    )
